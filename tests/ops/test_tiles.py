"""Tile grid math: extraction/blending invariants the distributed
upscaler depends on (identity round-trip, order independence)."""

import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.ops import tiles


def test_grid_covers_image():
    grid = tiles.calculate_tiles(300, 500, 128, 128, padding=16)
    assert grid.rows == 3 and grid.cols == 4
    covered = np.zeros((300, 500), dtype=bool)
    for y, x in grid.positions:
        assert 0 <= y <= 300 - 128 and 0 <= x <= 500 - 128
        covered[y : y + 128, x : x + 128] = True
    assert covered.all()


def test_grid_small_image_single_tile():
    grid = tiles.calculate_tiles(64, 64, 128, 128, padding=8)
    assert grid.num_tiles == 1
    assert grid.tile_h == 64 and grid.tile_w == 64


def test_extract_shapes():
    grid = tiles.calculate_tiles(100, 140, 64, 64, padding=8)
    imgs = jnp.zeros((2, 100, 140, 3))
    out = tiles.extract_tiles(imgs, grid)
    assert out.shape == (grid.num_tiles, 2, 64 + 16, 64 + 16, 3)


def test_blend_identity_roundtrip():
    """Extract then blend unprocessed tiles ⇒ the original image."""
    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.random((1, 96, 160, 3)), dtype=jnp.float32)
    grid = tiles.calculate_tiles(96, 160, 64, 64, padding=16)
    extracted = tiles.extract_tiles(img, grid)
    blended = tiles.blend_tiles(extracted, grid)
    np.testing.assert_allclose(np.asarray(blended), np.asarray(img), atol=1e-5)


def test_blend_order_independent():
    rng = np.random.default_rng(1)
    img = jnp.asarray(rng.random((1, 96, 96, 3)), dtype=jnp.float32)
    grid = tiles.calculate_tiles(96, 96, 64, 64, padding=8)
    extracted = tiles.extract_tiles(img, grid)
    perm = np.random.default_rng(2).permutation(grid.num_tiles)
    # Permuting tiles requires permuting positions consistently — emulate
    # by blending a permuted grid.
    permuted_grid = tiles.TileGrid(
        image_h=grid.image_h,
        image_w=grid.image_w,
        tile_h=grid.tile_h,
        tile_w=grid.tile_w,
        padding=grid.padding,
        rows=grid.rows,
        cols=grid.cols,
        positions=tuple(grid.positions[i] for i in perm),
    )
    a = tiles.blend_tiles(extracted, grid)
    b = tiles.blend_tiles(extracted[perm], permuted_grid)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_blend_single_tile_composites_core():
    grid = tiles.calculate_tiles(64, 64, 64, 64, padding=8)
    canvas = jnp.zeros((1, 64, 64, 3))
    tile = jnp.ones((1, grid.padded_h, grid.padded_w, 3))
    out = tiles.blend_single_tile(canvas, tile, 0, 0, grid)
    # Tile core (away from feather ring) fully replaces the canvas.
    core = np.asarray(out)[0, 16:48, 16:48, :]
    np.testing.assert_allclose(core, 1.0, atol=1e-6)


def test_upscale_nearest():
    img = jnp.arange(4.0).reshape(1, 2, 2, 1)
    up = tiles.upscale_nearest(img, 2)
    assert up.shape == (1, 4, 4, 1)
    assert float(up[0, 0, 0, 0]) == 0.0 and float(up[0, 3, 3, 0]) == 3.0


def test_tiled_vae_decode_shapes_and_rough_stats():
    """Tiled decode matches full-decode shape; statistics stay in the
    same regime (exact equality is impossible: GroupNorm stats are
    per-tile — the inherent tiled-VAE approximation). The small-input
    fast path must be exact."""
    from comfyui_distributed_tpu.models import pipeline as pl
    from comfyui_distributed_tpu.ops.tiled_vae import decode_tiled, encode_tiled

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    z = jnp.asarray(np.random.default_rng(5).random((1, 24, 24, 4)), jnp.float32)
    full = bundle.vae.apply(bundle.params["vae"], z, method="decode")
    tiled = decode_tiled(pl._Static(bundle), bundle.params["vae"], z,
                         tile=16, overlap=4)
    assert tiled.shape == full.shape
    assert np.isfinite(np.asarray(tiled)).all()
    assert abs(float(tiled.mean()) - float(full.mean())) < 0.2

    # small input takes the exact single-pass fast path
    z_small = z[:, :12, :12, :]
    exact = decode_tiled(pl._Static(bundle), bundle.params["vae"], z_small,
                         tile=16, overlap=4)
    ref = bundle.vae.apply(bundle.params["vae"], z_small, method="decode")
    np.testing.assert_allclose(np.asarray(exact), np.asarray(ref), atol=2e-2)  # jit vs eager bf16 fusion tolerance

    px = jnp.asarray(np.random.default_rng(6).random((1, 96, 96, 3)), jnp.float32)
    enc_full = bundle.vae.apply(bundle.params["vae"], px, method="encode")
    enc_tiled = encode_tiled(pl._Static(bundle), bundle.params["vae"], px,
                             tile=64, overlap=16)
    assert enc_tiled.shape == enc_full.shape


def test_upscale_model_random_init_is_bilinear():
    from comfyui_distributed_tpu.models.upscaler import load_upscale_model
    import jax

    model = load_upscale_model("2x-test")
    assert model.scale == 2
    img = jnp.asarray(np.random.default_rng(7).random((1, 16, 16, 3)), jnp.float32)
    out = model.upscale(img)
    assert out.shape == (1, 32, 32, 3)
    ref = jnp.clip(jax.image.resize(img, (1, 32, 32, 3), method="linear"), 0, 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_host_canvas_matches_jax_canvas():
    """The native/host blend path must be math-identical to the jax
    IncrementalCanvas (the elastic tier swaps between them)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from comfyui_distributed_tpu.ops import tiles as tile_ops

    grid = tile_ops.calculate_tiles(96, 96, 48, 8)
    base = jax.random.uniform(jax.random.key(0), (1, 96, 96, 3))
    jc = tile_ops.IncrementalCanvas(base, grid)
    hc = tile_ops.HostIncrementalCanvas(base, grid)
    for idx, (y, x) in enumerate(grid.positions):
        tile = jax.random.uniform(
            jax.random.key(idx + 1), (1, grid.padded_h, grid.padded_w, 3)
        )
        jc.blend(tile, y, x)
        hc.blend(tile, y, x)
    np.testing.assert_allclose(
        np.asarray(jc.result()), np.asarray(hc.result()), atol=1e-6
    )


def test_blend_segment_matches_scan():
    """The segment-sum (scatter) blend must equal the sequential-scan
    blend on the same tiles."""
    import jax
    import numpy as np

    from comfyui_distributed_tpu.ops import tiles as tile_ops

    for hw, tile, pad in ((96, 48, 8), (80, 32, 8)):
        grid = tile_ops.calculate_tiles(hw, hw, tile, pad)
        assert grid.num_tiles >= 4
        tiles = jax.random.uniform(
            jax.random.key(3),
            (grid.num_tiles, 2, grid.padded_h, grid.padded_w, 3),
        )
        a = tile_ops._blend_tiles_segment(tiles, grid)
        b = tile_ops._blend_tiles_scan(tiles, grid)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)
