"""Multi-entry conditioning composition (samplers.composite_eps):
the calc_cond_batch semantics behind ConditioningCombine / SetArea /
SetMask / SetTimestepRange — verified against a stub model so the
spatial/weight math is exact."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.ops import samplers as smp
from comfyui_distributed_tpu.ops.conditioning import Conditioning

pytestmark = pytest.mark.slow


def _entry(value, **kw):
    """A conditioning entry whose stub-model prediction is `value`
    everywhere (the context array carries the value in [0,0,0])."""
    return Conditioning(
        context=jnp.full((1, 1, 1), float(value)), **kw
    )


def _stub_model(x, sigma, cond):
    # per-batch-element value so the 2B-concat CFG fast path (pos and
    # neg stacked on the batch axis) keeps each half's own prediction
    vals = jnp.asarray(cond.context)[:, 0, 0]
    if vals.shape[0] != x.shape[0]:
        vals = jnp.broadcast_to(vals[:1], (x.shape[0],))
    return jnp.ones_like(x) * vals.reshape((-1,) + (1,) * (x.ndim - 1))


X = jnp.zeros((1, 8, 8, 4))
SIGMA = jnp.asarray([5.0])


def test_single_full_entry_is_identity():
    out = smp.composite_eps(_stub_model, X, SIGMA, _entry(3.0))
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_areas_compose_regionally():
    """Left half from entry A, right half from entry B (areas in
    pixels, latent = //8)."""
    a = _entry(1.0, area=(64, 32, 0, 0))    # (h, w, y, x) pixels
    b = _entry(2.0, area=(64, 32, 0, 32))
    out = np.asarray(smp.composite_eps(_stub_model, X, SIGMA, [a, b]))
    np.testing.assert_allclose(out[:, :, :4], 1.0)
    np.testing.assert_allclose(out[:, :, 4:], 2.0)


def test_overlap_normalizes_by_weight():
    """Full-frame entries average; strength weights the mean."""
    a = _entry(1.0, strength=1.0)
    b = _entry(4.0, strength=3.0)
    out = np.asarray(smp.composite_eps(_stub_model, X, SIGMA, [a, b]))
    np.testing.assert_allclose(out, (1.0 * 1 + 4.0 * 3) / 4, rtol=1e-6)


def test_uncovered_region_gets_zero_eps():
    a = _entry(5.0, area=(32, 64, 0, 0))  # top half only
    out = np.asarray(smp.composite_eps(_stub_model, X, SIGMA, [a]))
    np.testing.assert_allclose(out[:, :4], 5.0)
    np.testing.assert_allclose(out[:, 4:], 0.0)


def test_off_frame_area_origin_is_clamped():
    """An area whose origin lands at/past the latent edge must not
    slice a zero-size crop (which would crash the model trace)."""
    a = _entry(3.0, area=(64, 512, 0, 512))  # x=512px = cell 64 = edge
    out = np.asarray(smp.composite_eps(_stub_model, X, SIGMA, [a]))
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out[:, :, -1], 3.0)  # clamped to last col


def test_percentage_area_resolves_against_latent():
    """('percentage', ...) areas resolve at trace time against the
    actual latent shape — a half-width fraction covers exactly half of
    ANY canvas."""
    a = _entry(1.0, area=("percentage", 1.0, 0.5, 0.0, 0.0))
    b = _entry(2.0, area=("percentage", 1.0, 0.5, 0.0, 0.5))
    out = np.asarray(smp.composite_eps(_stub_model, X, SIGMA, [a, b]))
    np.testing.assert_allclose(out[:, :, :4], 1.0)
    np.testing.assert_allclose(out[:, :, 4:], 2.0)


def test_mask_weights_spatially():
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, :, 4:] = 1.0
    a = _entry(2.0, mask=jnp.asarray(mask))
    b = _entry(6.0)
    out = np.asarray(smp.composite_eps(_stub_model, X, SIGMA, [a, b]))
    np.testing.assert_allclose(out[:, :, :4], 6.0)      # mask 0 ⇒ b only
    np.testing.assert_allclose(out[:, :, 4:], 4.0)      # equal-weight mean


def test_timestep_window_gates_by_sigma():
    """Entry active only for the first half of sampling: contributes at
    high sigma, drops out at low sigma."""
    early = _entry(10.0, timestep_range=(0.0, 0.5))
    base = _entry(2.0)
    hi = np.asarray(
        smp.composite_eps(_stub_model, X, jnp.asarray([10.0]), [early, base])
    )
    np.testing.assert_allclose(hi, 6.0)  # both active: mean(10, 2)
    lo = np.asarray(
        smp.composite_eps(_stub_model, X, jnp.asarray([0.05]), [early, base])
    )
    np.testing.assert_allclose(lo, 2.0)  # window closed: base only


def test_area_crops_spatial_payloads():
    """An area-restricted entry's concat_latent and control_hint are
    CROPPED to the window, not squashed — the stub model returns its
    concat channel mean so misalignment would shift the value."""
    concat = jnp.zeros((1, 8, 8, 2)).at[:, :, 4:, :].set(8.0)

    def probe_model(x, sigma, cond):
        c = cond.concat_latent
        assert c.shape[1:3] == x.shape[1:3]  # cropped, not full-plane
        return jnp.full_like(x, float(c.mean()))

    # right-half area: the crop of concat is all 8.0
    e = _entry(0.0, area=(64, 32, 0, 32))
    e.concat_latent = concat
    out = np.asarray(smp.composite_eps(probe_model, X, SIGMA, [e]))
    np.testing.assert_allclose(out[:, :, 4:], 8.0)


def test_cfg_eval_routes_lists_through_composition():
    pos = [_entry(1.0, area=(64, 32, 0, 0)), _entry(2.0, area=(64, 32, 0, 32))]
    neg = _entry(0.0)
    _eps_pos, guided = smp._cfg_eval(
        _stub_model, 2.0, X, SIGMA, (pos, neg)
    )
    out = np.asarray(guided)  # eps_neg + 2*(eps_pos - eps_neg) = 2*eps_pos
    np.testing.assert_allclose(out[:, :, :4], 2.0)
    np.testing.assert_allclose(out[:, :, 4:], 4.0)


def test_single_unrestricted_keeps_batched_fast_path():
    """No areas/masks/windows ⇒ the 2B-batched CFG path still runs
    (same numbers as composition, one model call)."""
    pos = _entry(3.0)
    neg = _entry(1.0)
    _eps, guided = smp._cfg_eval(_stub_model, 2.0, X, SIGMA, (pos, neg))
    np.testing.assert_allclose(np.asarray(guided), 1.0 + 2.0 * (3.0 - 1.0))
