"""DeviceCanvas ≡ DeterministicHostCanvas bit-identity.

The device-resident hot path routes master-local grants through
DeviceCanvas so only ONE composited canvas crosses d2h per flush. The
swap is only sound if the composite is bit-identical to the
deterministic host canvas on every grid shape the elastic tier can
produce — these tests pin exact equality (assert_array_equal, no
tolerance), including ragged/non-uniform grids and shuffled arrival
order.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.ops import tiles as tile_ops

pytestmark = pytest.mark.fast


def _random_tiles(grid, batch=1, channels=3, seed=7):
    """One random processed tile per grid position, keyed by origin."""
    out = {}
    for idx, (y, x) in enumerate(grid.positions):
        out[(y, x)] = jax.random.uniform(
            jax.random.key(seed + idx),
            (batch, grid.padded_h, grid.padded_w, channels),
        )
    return out


def _parity_case(grid, batch=1, seed=3, order=None):
    base = jax.random.uniform(jax.random.key(seed), (batch, grid.image_h, grid.image_w, 3))
    tiles = _random_tiles(grid, batch=batch, seed=seed + 11)
    device = tile_ops.DeviceCanvas(base, grid)
    host = tile_ops.DeterministicHostCanvas(base, grid)
    positions = list(tiles)
    if order is not None:
        positions = [positions[i] for i in order]
    for y, x in positions:
        device.blend(tiles[(y, x)], y, x)
        host.blend(np.asarray(tiles[(y, x)]), y, x)
    return np.asarray(device.result()), np.asarray(host.result())


@pytest.mark.parametrize(
    "h,w,tile,pad",
    [
        (96, 96, 48, 8),     # even grid, overlap ring
        (100, 140, 64, 8),   # ragged: last row/col shifted (uniform)
        (300, 500, 128, 16), # larger ragged grid
        (64, 64, 128, 8),    # single tile smaller than requested
    ],
)
def test_device_canvas_bit_identical_to_host(h, w, tile, pad):
    grid = tile_ops.calculate_tiles(h, w, tile, tile, padding=pad)
    dev, host = _parity_case(grid)
    np.testing.assert_array_equal(dev, host)


def test_device_canvas_bit_identical_non_uniform_grid():
    """Non-uniform seam positions overhang the image; the padded canvas
    grows an edge strip. Device and host must crop identically."""
    grid = tile_ops.calculate_tiles(100, 140, 64, 64, padding=8, uniform=False)
    dev, host = _parity_case(grid, seed=5)
    np.testing.assert_array_equal(dev, host)


def test_device_canvas_bit_identical_with_mask_blur():
    grid = tile_ops.calculate_tiles(96, 96, 48, 48, padding=16, mask_blur=4)
    dev, host = _parity_case(grid, seed=9)
    np.testing.assert_array_equal(dev, host)


def test_device_canvas_arrival_order_is_immaterial():
    """Sorted compositing makes arrival order irrelevant — the chaos
    property (crash/speculation reorder grants) reduced to its core."""
    grid = tile_ops.calculate_tiles(96, 160, 64, 64, padding=8)
    rng = np.random.default_rng(17)
    order = list(rng.permutation(grid.num_tiles))
    dev_shuffled, host_sorted = _parity_case(grid, seed=13, order=order)
    dev_inorder, _ = _parity_case(grid, seed=13)
    np.testing.assert_array_equal(dev_shuffled, host_sorted)
    np.testing.assert_array_equal(dev_shuffled, dev_inorder)


def test_device_canvas_last_write_wins_dedup():
    """Re-blending a tile (speculation / duplicate grant) keeps the
    last payload and does not double-composite."""
    grid = tile_ops.calculate_tiles(96, 96, 48, 48, padding=8)
    base = jax.random.uniform(jax.random.key(21), (1, 96, 96, 3))
    tiles = _random_tiles(grid, seed=23)
    canvas = tile_ops.DeviceCanvas(base, grid)
    reference = tile_ops.DeviceCanvas(base, grid)
    first = True
    for (y, x), tile in tiles.items():
        if first:
            # a stale speculative payload, later overwritten
            canvas.blend(jnp.zeros_like(tile), y, x)
            first = False
        canvas.blend(tile, y, x)
        reference.blend(tile, y, x)
    assert canvas.tile_count == grid.num_tiles
    np.testing.assert_array_equal(
        np.asarray(canvas.result()), np.asarray(reference.result())
    )


def test_device_canvas_result_stays_on_device():
    """result() must hand back a jax.Array (the caller owns the single
    d2h transfer and its ledger note) and accept host tiles too —
    remote PNG tiles upload once at blend()."""
    grid = tile_ops.calculate_tiles(64, 64, 32, 32, padding=8)
    base = jnp.zeros((1, 64, 64, 3), dtype=jnp.float32)
    canvas = tile_ops.DeviceCanvas(base, grid)
    for y, x in grid.positions:
        host_tile = np.ones((1, grid.padded_h, grid.padded_w, 3), dtype=np.float32)
        canvas.blend(host_tile, y, x)
    out = canvas.result()
    assert isinstance(out, jax.Array)
    assert out.shape == (1, 64, 64, 3)
    assert out.dtype == jnp.float32


def test_device_canvas_empty_flush_returns_base():
    grid = tile_ops.calculate_tiles(64, 64, 32, 32, padding=8)
    base = jax.random.uniform(jax.random.key(29), (1, 64, 64, 3))
    canvas = tile_ops.DeviceCanvas(base, grid)
    assert canvas.tile_count == 0
    np.testing.assert_array_equal(
        np.asarray(canvas.result()), np.asarray(base, dtype=np.float32)
    )
