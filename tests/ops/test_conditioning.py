"""Conditioning structure: pytree behavior, tile cropping parity, and
ControlNet integration through txt2img and tiled upscale."""

import jax
import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models.controlnet import load_controlnet
from comfyui_distributed_tpu.ops import tiles as tile_ops
from comfyui_distributed_tpu.ops import upscale as up
from comfyui_distributed_tpu.ops.conditioning import (
    Conditioning,
    as_conditioning,
    crop_to_tile,
    slice_batch,
)


def test_conditioning_is_pytree():
    cond = Conditioning(
        context=jnp.ones((1, 4, 8)),
        control_hint=jnp.ones((1, 16, 16, 3)),
        control_strength=0.5,
        area=(8, 8, 0, 0),
    )
    leaves = jax.tree_util.tree_leaves(cond)
    assert len(leaves) == 2  # context + hint
    mapped = jax.tree_util.tree_map(lambda a: a * 2, cond)
    assert isinstance(mapped, Conditioning)
    assert mapped.control_strength == 0.5 and mapped.area == (8, 8, 0, 0)
    np.testing.assert_array_equal(np.asarray(mapped.context), 2.0)


def test_crop_to_tile_hint_and_area():
    hint = jnp.arange(32 * 32, dtype=jnp.float32).reshape(1, 32, 32, 1)
    cond = Conditioning(
        context=jnp.zeros((1, 2, 4)), control_hint=hint, area=(16, 16, 8, 8)
    )
    cropped = crop_to_tile(cond, y=8, x=8, tile_h=16, tile_w=16,
                           image_h=32, image_w=32)
    np.testing.assert_array_equal(
        np.asarray(cropped.control_hint[0, :, :, 0]),
        np.asarray(hint[0, 8:24, 8:24, 0]),
    )
    assert cropped.area == (16, 16, 0, 0)  # tile-local coords
    # area fully outside the tile zeroes the entry's strength
    gone = crop_to_tile(cond, y=0, x=0, tile_h=8, tile_w=8,
                        image_h=32, image_w=32)
    assert gone.area is None and gone.control_strength == 0.0


def test_slice_batch_follows_all_payloads():
    cond = Conditioning(
        context=jnp.arange(4 * 2 * 3, dtype=jnp.float32).reshape(4, 2, 3),
        control_hint=jnp.arange(4 * 4 * 4 * 1, dtype=jnp.float32).reshape(4, 4, 4, 1),
    )
    cut = slice_batch(cond, 1, 2)
    assert cut.context.shape == (2, 2, 3)
    np.testing.assert_array_equal(np.asarray(cut.context), np.asarray(cond.context[1:3]))
    assert cut.control_hint.shape == (2, 4, 4, 1)


def test_zero_init_controlnet_is_identity_on_txt2img():
    """Untrained ControlNet (zero-init output conv) must not change the
    sample — the wiring test that catches plumbing bugs."""
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    cn = load_controlnet("tile", model_channels=32, downscale=4)
    pos_plain = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    hint = jnp.ones((1, 32, 32, 3)) * 0.5
    pos_cn = Conditioning(
        context=pos_plain, control_hint=hint, control_strength=1.0,
        control_params=cn.params, control_module=cn.module,
    )
    base = pl.img2img_latents(
        bundle, jnp.zeros((1, 8, 8, 4)), pos_plain, neg, steps=2, denoise=1.0, seed=1
    )
    with_cn = pl.img2img_latents(
        bundle, jnp.zeros((1, 8, 8, 4)), pos_cn, neg, steps=2, denoise=1.0, seed=1
    )
    np.testing.assert_allclose(np.asarray(base), np.asarray(with_cn), atol=1e-6)


def test_upscale_with_controlnet_hint_runs_and_matches_mesh():
    from comfyui_distributed_tpu.parallel import build_mesh

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    cn = load_controlnet("tile", model_channels=32, downscale=4)
    img = jnp.asarray(np.random.default_rng(0).random((1, 64, 64, 3)), jnp.float32)
    pos = Conditioning(
        context=pl.encode_text(bundle, ["p"]), control_hint=img,
        control_strength=1.0, control_params=cn.params, control_module=cn.module,
    )
    neg = as_conditioning(pl.encode_text(bundle, [""]))
    kwargs = dict(upscale_by=2.0, tile=64, padding=16, steps=1, denoise=0.3, seed=2)
    single = up.run_upscale(bundle, img, pos, neg, mesh=None, **kwargs)
    assert single.shape == (1, 128, 128, 3)
    mesh = build_mesh({"data": 8})
    sharded = up.run_upscale(bundle, img, pos, neg, mesh=mesh, **kwargs)
    np.testing.assert_allclose(
        np.asarray(single), np.asarray(sharded), atol=2e-2, rtol=0
    )


def test_pooled_adm_conditioning_path():
    """SDXL-class pooled conditioning flows from the text encoder into
    the UNet label embedding and changes the output."""
    bundle = pl.load_pipeline("tiny-unet-adm", seed=0)
    pos = pl.encode_text_pooled(bundle, ["a castle"])
    neg = pl.encode_text_pooled(bundle, [""])
    # dual-encoder bundle: pooled comes from the projected second
    # encoder (tiny-te-g, proj_dim=96)
    assert pos.pooled is not None and pos.pooled.shape == (1, 96)
    # the zero-init output conv hides every internal signal; randomise
    # it so the adm path's effect is observable at the output
    params = jax.tree_util.tree_map(lambda a: a, bundle.params)
    out_conv = params["unet"]["params"]["out_conv"]
    out_conv["kernel"] = jax.random.normal(
        jax.random.key(9), out_conv["kernel"].shape
    ) * 0.05
    bundle.params = params

    latents = jnp.zeros((1, 8, 8, 4))
    out = pl.img2img_latents(bundle, latents, pos, neg, steps=2, denoise=1.0, seed=3)
    assert np.isfinite(np.asarray(out)).all()
    # zeroing the pooled vector must change the result (the adm path
    # is actually wired, not ignored)
    import dataclasses as dc

    pos_zero = dc.replace(pos, pooled=jnp.zeros_like(pos.pooled))
    neg_zero = dc.replace(neg, pooled=jnp.zeros_like(neg.pooled))
    out_zero = pl.img2img_latents(
        bundle, latents, pos_zero, neg_zero, steps=2, denoise=1.0, seed=3
    )
    assert not np.array_equal(np.asarray(out), np.asarray(out_zero))


# --- round-2 parity tail: GLIGEN / reference_latents / model patches ------

def _mk(ctx_batch=1):
    import jax.numpy as jnp

    from comfyui_distributed_tpu.ops.conditioning import Conditioning

    return Conditioning(context=jnp.zeros((ctx_batch, 4, 8)))


def test_gligen_box_window_math():
    """Reference crop_gligen parity: latent boxes scale x8 to pixels,
    intersect with the tile, re-origin, and return to latent units."""
    import jax.numpy as jnp

    from comfyui_distributed_tpu.ops.conditioning import crop_to_tile

    cond = _mk()
    # box: 16x16 latent units at (y=4, x=8) => pixels (32,64)-(160,192)
    cond.gligen_embs = jnp.ones((2, 8))
    cond.gligen_boxes = ((16, 16, 4, 8), (4, 4, 60, 60))
    out = crop_to_tile(cond, y=64, x=64, tile_h=128, tile_w=128,
                       image_h=512, image_w=512)
    # intersection with tile (64..192, 64..192): y 64..160, x 64..192
    # tile-local: y 0..96, x 0..192->128... x2=min(192,192)=192-64=128
    assert out.gligen_active == (True, False)
    h, w, y, x = out.gligen_boxes[0]
    assert (h, w, y, x) == (96 // 8, 128 // 8, 0, 0)
    # second box at latent (60,60) => pixels 480.. outside the tile
    assert out.gligen_boxes[1] == (0, 0, 0, 0)


def test_gligen_box_fully_inside():
    import jax.numpy as jnp

    from comfyui_distributed_tpu.ops.conditioning import crop_to_tile

    cond = _mk()
    cond.gligen_embs = jnp.ones((1, 8))
    cond.gligen_boxes = ((8, 8, 12, 12),)  # pixels (96,96)-(160,160)
    out = crop_to_tile(cond, y=64, x=64, tile_h=128, tile_w=128,
                       image_h=512, image_w=512)
    assert out.gligen_active == (True,)
    # tile-local pixel box (32,32)-(96,96) => latent (8,8) at (4,4)
    assert out.gligen_boxes[0] == (8, 8, 4, 4)


def test_reference_latents_windowed_to_tile():
    import jax.numpy as jnp
    import numpy as np

    from comfyui_distributed_tpu.ops.conditioning import crop_to_tile

    cond = _mk()
    # canvas 256px -> latent 32; distinctive gradient to verify window
    lat = jnp.arange(32 * 32, dtype=jnp.float32).reshape(1, 32, 32, 1)
    cond.reference_latents = [lat]
    out = crop_to_tile(cond, y=64, x=128, tile_h=64, tile_w=64,
                       image_h=256, image_w=256)
    got = out.reference_latents[0]
    assert got.shape == (1, 8, 8, 1)
    expect = np.asarray(lat)[:, 8:16, 16:24, :]
    np.testing.assert_allclose(np.asarray(got), expect, atol=1e-5)


def test_reference_latents_resized_when_mismatched():
    import jax.numpy as jnp

    from comfyui_distributed_tpu.ops.conditioning import crop_to_tile

    cond = _mk()
    cond.reference_latents = [jnp.ones((1, 16, 16, 4))]  # not canvas-sized
    out = crop_to_tile(cond, y=0, x=0, tile_h=128, tile_w=128,
                       image_h=512, image_w=512)
    assert out.reference_latents[0].shape == (1, 16, 16, 4)


def test_model_patches_crop_like_hints():
    import jax.numpy as jnp
    import numpy as np

    from comfyui_distributed_tpu.ops.conditioning import crop_to_tile

    cond = _mk()
    patch = jnp.arange(64 * 64, dtype=jnp.float32).reshape(1, 64, 64, 1)
    cond.model_patches = {"diffsynth_hint": patch}
    out = crop_to_tile(cond, y=16, x=32, tile_h=16, tile_w=16,
                       image_h=64, image_w=64)
    got = out.model_patches["diffsynth_hint"]
    assert got.shape == (1, 16, 16, 1)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(patch)[:, 16:32, 32:48, :]
    )


def test_slice_batch_covers_new_payloads():
    import jax.numpy as jnp

    from comfyui_distributed_tpu.ops.conditioning import Conditioning, slice_batch

    cond = Conditioning(
        context=jnp.zeros((4, 4, 8)),
        reference_latents=[jnp.zeros((4, 8, 8, 4))],
        model_patches={"p": jnp.zeros((4, 16, 16, 1))},
    )
    out = slice_batch(cond, 1, 2)
    assert out.context.shape[0] == 2
    assert out.reference_latents[0].shape[0] == 2
    assert out.model_patches["p"].shape[0] == 2


def test_traced_tile_cond_reference_latents_and_patches():
    """The mesh/scan path: prep pads to the canvas+padding grid, then
    traced origins slice constant-size windows."""
    import jax
    import jax.numpy as jnp

    from comfyui_distributed_tpu.ops import tiles as tile_ops
    from comfyui_distributed_tpu.ops import upscale as up
    from comfyui_distributed_tpu.ops.conditioning import Conditioning

    grid = tile_ops.calculate_tiles(128, 128, 64, 16)
    cond = Conditioning(
        context=jnp.zeros((1, 4, 8)),
        reference_latents=[jnp.zeros((1, 16, 16, 4))],
        model_patches={"p": jnp.zeros((1, 128, 128, 1))},
    )
    prepped = up.prep_cond_for_tiles(cond, grid)
    k = 8
    assert prepped.model_patches["p"].shape[1] == 128 + 2 * grid.padding
    assert prepped.reference_latents[0].shape[1] == (128 + 2 * grid.padding) // k

    def slice_at(y, x):
        c = up.tile_cond(prepped, y, x, grid)
        return c.reference_latents[0], c.model_patches["p"]

    lat, patch = jax.jit(slice_at)(jnp.int32(16), jnp.int32(64))
    assert lat.shape == (1, grid.padded_h // k, grid.padded_w // k, 4)
    assert patch.shape == (1, grid.padded_h, grid.padded_w, 1)
