"""Conditioning structure: pytree behavior, tile cropping parity, and
ControlNet integration through txt2img and tiled upscale."""

import jax
import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models.controlnet import load_controlnet
from comfyui_distributed_tpu.ops import tiles as tile_ops
from comfyui_distributed_tpu.ops import upscale as up
from comfyui_distributed_tpu.ops.conditioning import (
    Conditioning,
    as_conditioning,
    crop_to_tile,
    slice_batch,
)


def test_conditioning_is_pytree():
    cond = Conditioning(
        context=jnp.ones((1, 4, 8)),
        control_hint=jnp.ones((1, 16, 16, 3)),
        control_strength=0.5,
        area=(8, 8, 0, 0),
    )
    leaves = jax.tree_util.tree_leaves(cond)
    assert len(leaves) == 2  # context + hint
    mapped = jax.tree_util.tree_map(lambda a: a * 2, cond)
    assert isinstance(mapped, Conditioning)
    assert mapped.control_strength == 0.5 and mapped.area == (8, 8, 0, 0)
    np.testing.assert_array_equal(np.asarray(mapped.context), 2.0)


def test_crop_to_tile_hint_and_area():
    hint = jnp.arange(32 * 32, dtype=jnp.float32).reshape(1, 32, 32, 1)
    cond = Conditioning(
        context=jnp.zeros((1, 2, 4)), control_hint=hint, area=(16, 16, 8, 8)
    )
    cropped = crop_to_tile(cond, y=8, x=8, tile_h=16, tile_w=16,
                           image_h=32, image_w=32)
    np.testing.assert_array_equal(
        np.asarray(cropped.control_hint[0, :, :, 0]),
        np.asarray(hint[0, 8:24, 8:24, 0]),
    )
    assert cropped.area == (16, 16, 0, 0)  # tile-local coords
    # area fully outside the tile zeroes the entry's strength
    gone = crop_to_tile(cond, y=0, x=0, tile_h=8, tile_w=8,
                        image_h=32, image_w=32)
    assert gone.area is None and gone.control_strength == 0.0


def test_slice_batch_follows_all_payloads():
    cond = Conditioning(
        context=jnp.arange(4 * 2 * 3, dtype=jnp.float32).reshape(4, 2, 3),
        control_hint=jnp.arange(4 * 4 * 4 * 1, dtype=jnp.float32).reshape(4, 4, 4, 1),
    )
    cut = slice_batch(cond, 1, 2)
    assert cut.context.shape == (2, 2, 3)
    np.testing.assert_array_equal(np.asarray(cut.context), np.asarray(cond.context[1:3]))
    assert cut.control_hint.shape == (2, 4, 4, 1)


def test_zero_init_controlnet_is_identity_on_txt2img():
    """Untrained ControlNet (zero-init output conv) must not change the
    sample — the wiring test that catches plumbing bugs."""
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    cn = load_controlnet("tile", model_channels=32, downscale=4)
    pos_plain = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    hint = jnp.ones((1, 32, 32, 3)) * 0.5
    pos_cn = Conditioning(
        context=pos_plain, control_hint=hint, control_strength=1.0,
        control_params=cn.params, control_module=cn.module,
    )
    base = pl.img2img_latents(
        bundle, jnp.zeros((1, 8, 8, 4)), pos_plain, neg, steps=2, denoise=1.0, seed=1
    )
    with_cn = pl.img2img_latents(
        bundle, jnp.zeros((1, 8, 8, 4)), pos_cn, neg, steps=2, denoise=1.0, seed=1
    )
    np.testing.assert_allclose(np.asarray(base), np.asarray(with_cn), atol=1e-6)


def test_upscale_with_controlnet_hint_runs_and_matches_mesh():
    from comfyui_distributed_tpu.parallel import build_mesh

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    cn = load_controlnet("tile", model_channels=32, downscale=4)
    img = jnp.asarray(np.random.default_rng(0).random((1, 64, 64, 3)), jnp.float32)
    pos = Conditioning(
        context=pl.encode_text(bundle, ["p"]), control_hint=img,
        control_strength=1.0, control_params=cn.params, control_module=cn.module,
    )
    neg = as_conditioning(pl.encode_text(bundle, [""]))
    kwargs = dict(upscale_by=2.0, tile=64, padding=16, steps=1, denoise=0.3, seed=2)
    single = up.run_upscale(bundle, img, pos, neg, mesh=None, **kwargs)
    assert single.shape == (1, 128, 128, 3)
    mesh = build_mesh({"data": 8})
    sharded = up.run_upscale(bundle, img, pos, neg, mesh=mesh, **kwargs)
    np.testing.assert_allclose(
        np.asarray(single), np.asarray(sharded), atol=2e-2, rtol=0
    )


def test_pooled_adm_conditioning_path():
    """SDXL-class pooled conditioning flows from the text encoder into
    the UNet label embedding and changes the output."""
    bundle = pl.load_pipeline("tiny-unet-adm", seed=0)
    pos = pl.encode_text_pooled(bundle, ["a castle"])
    neg = pl.encode_text_pooled(bundle, [""])
    assert pos.pooled is not None and pos.pooled.shape == (1, 64)
    # the zero-init output conv hides every internal signal; randomise
    # it so the adm path's effect is observable at the output
    params = jax.tree_util.tree_map(lambda a: a, bundle.params)
    out_conv = params["unet"]["params"]["out_conv"]
    out_conv["kernel"] = jax.random.normal(
        jax.random.key(9), out_conv["kernel"].shape
    ) * 0.05
    bundle.params = params

    latents = jnp.zeros((1, 8, 8, 4))
    out = pl.img2img_latents(bundle, latents, pos, neg, steps=2, denoise=1.0, seed=3)
    assert np.isfinite(np.asarray(out)).all()
    # zeroing the pooled vector must change the result (the adm path
    # is actually wired, not ignored)
    import dataclasses as dc

    pos_zero = dc.replace(pos, pooled=jnp.zeros_like(pos.pooled))
    neg_zero = dc.replace(neg, pooled=jnp.zeros_like(neg.pooled))
    out_zero = pl.img2img_latents(
        bundle, latents, pos_zero, neg_zero, steps=2, denoise=1.0, seed=3
    )
    assert not np.array_equal(np.asarray(out), np.asarray(out_zero))
