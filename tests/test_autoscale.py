"""Autoscale policy units (scheduler/autoscale.py): burn-alert and
utilization triggers, patient scale-down, bounds, and the measured
chip-second cost/benefit ledger every decision must carry."""

import pytest

from comfyui_distributed_tpu.scheduler.autoscale import AutoscaleController

pytestmark = pytest.mark.fast


class Clock:
    def __init__(self, now=1000.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


class FakeSLO:
    def __init__(self):
        self.burning = set()

    def is_active(self, name):
        return name in self.burning


class FakeUsage:
    """Cumulative chip-second counter, like UsageAggregator.rollup()."""

    def __init__(self):
        self.chip_s = 0.0

    def rollup(self):
        return {"totals": {"chip_s": self.chip_s}}


class Fleet:
    """Launcher/drainer/capacity over an in-memory worker pool."""

    def __init__(self, workers=2, chips_each=1.0):
        self.workers = workers
        self.chips_each = chips_each
        self.launched = []
        self.drained = []

    def launcher(self):
        self.workers += 1
        wid = f"w{self.workers}"
        self.launched.append(wid)
        return wid

    def drainer(self):
        if self.workers <= 0:
            return None
        wid = f"w{self.workers}"
        self.workers -= 1
        self.drained.append(wid)
        return wid

    def capacity(self):
        return self.workers, self.workers * self.chips_each


def controller(clock, fleet, slo=None, usage=None, **kw):
    kw.setdefault("interval", 10.0)
    kw.setdefault("min_workers", 1)
    kw.setdefault("max_workers", 4)
    kw.setdefault("target_util", 0.70)
    kw.setdefault("down_hold", 60.0)
    return AutoscaleController(
        slo=slo, usage=usage,
        launcher=fleet.launcher, drainer=fleet.drainer,
        capacity_fn=fleet.capacity, clock=clock, **kw,
    )


def test_burn_alert_forces_scale_up():
    clock, fleet, slo = Clock(), Fleet(workers=2), FakeSLO()
    ctrl = controller(clock, fleet, slo=slo, usage=FakeUsage())
    slo.burning.add("tile_latency")
    record = ctrl.step()
    assert record["action"] == "scale_up"
    assert "burn:tile_latency" in record["reason"]
    assert record["burn_alerts"] == ["tile_latency"]
    assert fleet.launched == ["w3"]


def test_journal_latency_burn_is_not_a_scale_signal():
    """journal_latency burns point at the disk — more workers would
    add journal load, not relieve it."""
    clock, fleet, slo = Clock(), Fleet(workers=2), FakeSLO()
    ctrl = controller(clock, fleet, slo=slo, usage=FakeUsage())
    slo.burning.add("journal_latency")
    assert ctrl.step()["action"] == "hold"
    assert fleet.launched == []


def test_high_utilization_scales_up():
    clock, fleet, usage = Clock(), Fleet(workers=2), FakeUsage()
    ctrl = controller(clock, fleet, usage=usage)
    ctrl.step()  # baseline: establishes the cumulative counter
    # window: 10s elapsed, 2 chips => 20 chip-s capacity; demand 18
    clock.advance(10.0)
    usage.chip_s += 18.0
    record = ctrl.step()
    assert record["action"] == "scale_up"
    assert record["utilization"] == pytest.approx(0.9)
    assert record["demand_chip_s"] == pytest.approx(18.0)
    assert record["capacity_chip_s"] == pytest.approx(20.0)


def test_scale_up_is_bounded_by_max_workers():
    clock, fleet, slo = Clock(), Fleet(workers=4), FakeSLO()
    ctrl = controller(clock, fleet, slo=slo, usage=FakeUsage(), max_workers=4)
    slo.burning.add("availability")
    record = ctrl.step()
    assert record["action"] == "hold"
    assert "max_workers" in record["reason"]
    assert fleet.launched == []


def test_scale_down_waits_out_the_hold_window():
    clock, fleet, usage = Clock(), Fleet(workers=3), FakeUsage()
    ctrl = controller(clock, fleet, usage=usage, down_hold=60.0)
    ctrl.step()  # baseline
    for _ in range(5):  # 50s of near-idle: still held
        clock.advance(10.0)
        usage.chip_s += 0.5
        record = ctrl.step()
        assert record["action"] == "hold", record
    clock.advance(10.0)
    usage.chip_s += 0.5
    record = ctrl.step()
    assert record["action"] == "scale_down"
    assert fleet.drained == ["w3"]
    assert fleet.workers == 2


def test_pressure_resets_the_scale_down_hold():
    clock, fleet, usage = Clock(), Fleet(workers=3), FakeUsage()
    slo = FakeSLO()
    ctrl = controller(clock, fleet, slo=slo, usage=usage, down_hold=30.0,
                      max_workers=3)
    ctrl.step()
    clock.advance(10.0); ctrl.step()          # idle 10s
    clock.advance(10.0); ctrl.step()          # idle 20s
    slo.burning.add("deadline_miss")          # pressure: window resets
    clock.advance(10.0); ctrl.step()
    slo.burning.clear()
    clock.advance(10.0)
    record = ctrl.step()                      # idle again, held from zero
    assert record["action"] == "hold"
    assert fleet.drained == []


def test_scale_down_respects_min_workers():
    clock, fleet, usage = Clock(), Fleet(workers=1), FakeUsage()
    ctrl = controller(clock, fleet, usage=usage, min_workers=1, down_hold=0.0)
    ctrl.step()
    clock.advance(10.0)
    assert ctrl.step()["action"] == "hold"
    assert fleet.drained == []


def test_decisions_carry_measured_cost_benefit():
    """The record settled one window later must show the chip-second
    capacity delta the action bought — the operator's cost line."""
    clock, fleet, usage, slo = Clock(), Fleet(workers=2), FakeUsage(), FakeSLO()
    ctrl = controller(clock, fleet, slo=slo, usage=usage)
    ctrl.step()  # baseline hold
    slo.burning.add("tile_latency")
    clock.advance(10.0)
    usage.chip_s += 19.0
    up = ctrl.step()  # scale_up: fleet 2 -> 3 chips
    assert up["action"] == "scale_up" and up["measured"] is None
    slo.burning.clear()
    clock.advance(10.0)
    usage.chip_s += 19.0
    ctrl.step()
    # the scale_up record is now settled with what the action bought
    assert up["measured"] is not None
    # capacity went from 2 chips x 10s to 3 chips x 10s = +10 chip-s
    assert up["measured"]["capacity_delta_chip_s"] == pytest.approx(10.0)
    assert up["measured"]["utilization_after"] == pytest.approx(19.0 / 30.0,
                                                                abs=1e-3)


def test_actuation_failure_degrades_to_hold():
    clock, slo = Clock(), FakeSLO()
    slo.burning.add("availability")

    def broken_launcher():
        raise RuntimeError("node pool exhausted")

    ctrl = AutoscaleController(
        slo=slo, usage=FakeUsage(), launcher=broken_launcher,
        capacity_fn=lambda: (1, 1.0), clock=clock,
        interval=10.0, min_workers=1, max_workers=4,
        target_util=0.7, down_hold=60.0,
    )
    record = ctrl.step()
    assert record["action"] == "hold"
    assert "nothing launchable" in record["reason"]


def test_signal_failures_never_crash_the_step():
    class BrokenUsage:
        def rollup(self):
            raise OSError("metrics store down")

    class BrokenSLO:
        def is_active(self, name):
            raise RuntimeError("slo engine down")

    clock, fleet = Clock(), Fleet(workers=2)
    ctrl = controller(clock, fleet, slo=BrokenSLO(), usage=BrokenUsage())
    record = ctrl.step()
    assert record["action"] == "hold"
    assert record["burn_alerts"] == []


def test_status_surfaces_bounds_and_recent_decisions():
    clock, fleet = Clock(), Fleet(workers=2)
    ctrl = controller(clock, fleet, usage=FakeUsage())
    for _ in range(3):
        clock.advance(10.0)
        ctrl.step()
    status = ctrl.status(limit=2)
    assert status["enabled"] is True
    assert status["bounds"] == {"min": 1, "max": 4}
    assert len(status["decisions"]) == 2
    assert status["workers"] == 2


def test_background_loop_runs_and_stops():
    clock, fleet = Clock(), Fleet(workers=2)
    ctrl = controller(clock, fleet, usage=FakeUsage(), interval=0.02)
    ctrl.start()
    try:
        import time as _time

        deadline = _time.time() + 5.0
        while not ctrl.decisions and _time.time() < deadline:
            _time.sleep(0.01)
        assert ctrl.decisions, "loop never evaluated"
    finally:
        ctrl.stop()
    assert ctrl._thread is None
