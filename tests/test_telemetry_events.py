"""Event bus: pub/sub fan-out, type filtering, drop-oldest overflow,
and the forwarding hooks from metrics / tracing / health."""

import asyncio
import threading

from comfyui_distributed_tpu.resilience.health import get_health_registry
from comfyui_distributed_tpu.telemetry import get_event_bus, get_tracer
from comfyui_distributed_tpu.telemetry.events import EventBus
from comfyui_distributed_tpu.telemetry.instruments import store_pulls_total


def run(coro):
    return asyncio.run(coro)


# --- bus semantics ---------------------------------------------------------

def test_publish_without_subscribers_is_a_cheap_noop():
    bus = EventBus()
    bus.publish("anything", x=1)  # must not raise, must not count
    assert bus.published == 0


def test_subscriber_receives_typed_events_in_order():
    async def main():
        bus = EventBus(clock=lambda: 123.0)
        sub = bus.subscribe()
        bus.publish("a", n=1)
        bus.publish("b", n=2)
        await asyncio.sleep(0)
        first = await sub.get()
        second = await sub.get()
        assert [first["type"], second["type"]] == ["a", "b"]
        assert first["seq"] < second["seq"]
        assert first["ts"] == 123.0
        assert first["data"] == {"n": 1}

    run(main())


def test_type_filter_is_bus_side():
    async def main():
        bus = EventBus()
        sub = bus.subscribe(types=["wanted"])
        bus.publish("noise", n=1)
        bus.publish("wanted", n=2)
        await asyncio.sleep(0)
        event = await sub.get()
        assert event["type"] == "wanted"
        assert sub.queue.empty()

    run(main())


def test_overflow_drops_oldest_and_counts():
    async def main():
        bus = EventBus()
        sub = bus.subscribe(maxsize=3)
        for i in range(10):
            bus.publish("e", i=i)
        await asyncio.sleep(0)
        kept = []
        while not sub.queue.empty():
            kept.append((await sub.get())["data"]["i"])
        assert kept == [7, 8, 9], "drop-OLDEST: the tail survives"
        assert sub.dropped == 7

    run(main())


def test_unsubscribe_stops_delivery():
    async def main():
        bus = EventBus()
        sub = bus.subscribe()
        bus.unsubscribe(sub)
        bus.publish("e")
        await asyncio.sleep(0)
        assert sub.queue.empty()
        assert bus.subscriber_count == 0

    run(main())


def test_publish_is_thread_safe_across_threads():
    async def main():
        bus = EventBus()
        sub = bus.subscribe(maxsize=10000)

        def blast():
            for i in range(200):
                bus.publish("t", i=i)

        threads = [threading.Thread(target=blast) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # let the call_soon_threadsafe callbacks drain
        for _ in range(20):
            await asyncio.sleep(0.01)
            if sub.queue.qsize() == 800:
                break
        assert sub.queue.qsize() + sub.dropped == 800
        seqs = []
        while not sub.queue.empty():
            seqs.append((await sub.get())["seq"])
        assert seqs == sorted(seqs), "per-bus seq is monotonic"

    run(main())


# --- forwarding hooks ------------------------------------------------------

def test_metric_mutations_stream_as_metric_delta():
    async def main():
        bus = get_event_bus()
        sub = bus.subscribe(types=["metric_delta"])
        store_pulls_total().inc(worker_id="w1", outcome="task")
        await asyncio.sleep(0.01)
        event = await asyncio.wait_for(sub.get(), 2)
        assert event["data"]["metric"] == "cdt_store_pulls_total"
        assert event["data"]["kind"] == "counter"
        assert event["data"]["labels"] == {"worker_id": "w1", "outcome": "task"}
        assert event["data"]["value"] == 1.0
        bus.unsubscribe(sub)

    run(main())


def test_span_lifecycle_streams_open_and_close():
    async def main():
        bus = get_event_bus()
        sub = bus.subscribe(types=["span_open", "span_close"])
        with get_tracer().span("tile.sample", trace_id="exec_ev_1", stage="sample"):
            pass
        await asyncio.sleep(0.01)
        opened = await asyncio.wait_for(sub.get(), 2)
        closed = await asyncio.wait_for(sub.get(), 2)
        assert opened["type"] == "span_open"
        assert closed["type"] == "span_close"
        assert opened["data"]["trace_id"] == "exec_ev_1"
        assert closed["data"]["span_id"] == opened["data"]["span_id"]
        assert closed["data"]["duration"] is not None
        assert closed["data"]["status"] == "ok"
        bus.unsubscribe(sub)

    run(main())


def test_health_transitions_stream():
    async def main():
        bus = get_event_bus()
        sub = bus.subscribe(types=["health_transition"])
        registry = get_health_registry()
        registry.record_failure("w7")
        registry.record_failure("w7")  # → suspect
        await asyncio.sleep(0.01)
        event = await asyncio.wait_for(sub.get(), 2)
        assert event["data"] == {
            "worker_id": "w7",
            "from_state": "healthy",
            "to_state": "suspect",
        }
        bus.unsubscribe(sub)

    run(main())


def test_mark_suspect_fires_a_transition_event():
    async def main():
        bus = get_event_bus()
        sub = bus.subscribe(types=["health_transition"])
        registry = get_health_registry()
        assert registry.mark_suspect("w8").value == "suspect"
        # idempotent: second call is a no-op, no second event
        registry.mark_suspect("w8")
        await asyncio.sleep(0.01)
        event = await asyncio.wait_for(sub.get(), 2)
        assert event["data"]["to_state"] == "suspect"
        assert sub.queue.empty()
        bus.unsubscribe(sub)

    run(main())


def test_mark_suspect_leaves_quarantined_workers_alone():
    registry = get_health_registry()
    for _ in range(5):
        registry.record_failure("w9")
    assert registry.state("w9").value == "quarantined"
    assert registry.mark_suspect("w9").value == "quarantined"
