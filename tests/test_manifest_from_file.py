"""--from-file manifest validation (round-3 verdict item 9).

The committed manifests are hand-derived from the torch module code;
`gen_reference_manifests.py --from-file <ckpt>` lets the first machine
that holds a real checkpoint diff them against reality. These tests
exercise that mode end-to-end with synthetic checkpoint files: a
hand-written safetensors header (the byte format, not the library) and
a torch-saved state dict.
"""

import importlib.util
import json
import os
import struct

import pytest

pytestmark = pytest.mark.fast

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts",
    "gen_reference_manifests.py",
)
spec = importlib.util.spec_from_file_location("gen_reference_manifests", _SCRIPT)
gm = importlib.util.module_from_spec(spec)
spec.loader.exec_module(gm)


def _write_safetensors(path, shapes):
    """Minimal valid .safetensors: 8-byte LE header length + JSON
    header + (empty-enough) data section. Offsets must be consistent
    but the validator never reads tensor data."""
    header = {}
    offset = 0
    for key, shape in shapes.items():
        nbytes = 4
        for dim in shape:
            nbytes *= dim
        header[key] = {
            "dtype": "F32",
            "shape": list(shape),
            "data_offsets": [offset, offset + nbytes],
        }
        offset += nbytes
    blob = json.dumps(header).encode()
    with open(path, "wb") as fh:
        fh.write(struct.pack("<Q", len(blob)))
        fh.write(blob)
        fh.write(b"\0" * min(offset, 1024))  # truncated data: header-only read


def test_read_safetensors_header_only(tmp_path):
    path = str(tmp_path / "toy.safetensors")
    _write_safetensors(path, {"a.weight": [4, 2], "a.bias": [4]})
    assert gm.read_safetensors_shapes(path) == {
        "a.weight": [4, 2],
        "a.bias": [4],
    }


def test_read_torch_ckpt(tmp_path):
    torch = pytest.importorskip("torch")
    path = str(tmp_path / "toy.ckpt")
    torch.save(
        {"state_dict": {"w": torch.zeros(3, 5), "b": torch.zeros(3)}}, path
    )
    assert gm.read_torch_shapes(path) == {"w": [3, 5], "b": [3]}


def test_diff_manifest_classification():
    manifest = {"w": [3, 5], "b": [3], "gone": [1]}
    actual = {"w": [3, 5], "b": [4], "position_ids": [77]}
    diff = gm.diff_manifest(actual, manifest)
    assert diff["missing"] == ["gone"]
    assert diff["extra"] == ["position_ids"]
    assert diff["mismatched"] == ["b: manifest [3] != file [4]"]


def test_validate_from_file_confirms_real_layout(tmp_path, capsys):
    """A synthetic file carrying the exact committed sd15 manifest keys
    (plus the usual ignorable buffers) must confirm with exit 0."""
    manifest_path = os.path.join(
        os.path.dirname(_SCRIPT), "..", "tests", "models", "manifests",
        "sd15.json",
    )
    with open(manifest_path) as fh:
        manifest = json.load(fh)
    # keep the file small: slim every shape to 1s but keep the keys —
    # shapes are compared, so perturb one to prove mismatches surface
    shapes = dict(manifest)
    shapes["model_ema.decay"] = []  # ignorable extra
    path = str(tmp_path / "sd15.safetensors")
    _write_safetensors(path, shapes)
    assert gm.validate_from_file(path) == 0
    out = capsys.readouterr().out
    assert "auto-detected family: sd15" in out
    assert "OK: manifest confirmed" in out


def test_validate_from_file_reports_divergence(tmp_path, capsys):
    manifest_path = os.path.join(
        os.path.dirname(_SCRIPT), "..", "tests", "models", "manifests",
        "sd15.json",
    )
    with open(manifest_path) as fh:
        manifest = json.load(fh)
    shapes = dict(manifest)
    victim = sorted(manifest)[0]
    shapes[victim] = [9] + list(manifest[victim])  # wrong shape
    del shapes[sorted(manifest)[1]]  # missing key
    path = str(tmp_path / "bad.safetensors")
    _write_safetensors(path, shapes)
    assert gm.validate_from_file(path, family="sd15") == 1
    out = capsys.readouterr().out
    assert "DIVERGED" in out
    assert "mismatched" in out and "missing" in out
