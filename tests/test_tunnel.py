"""Tunnel manager with a fake cloudflared binary: URL capture, config
state swap/restore, stale-state recovery."""

import asyncio
import os
import stat

import pytest

from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils.exceptions import TunnelError
from comfyui_distributed_tpu.utils.tunnel import TunnelManager


@pytest.fixture()
def fake_cloudflared(tmp_path, monkeypatch):
    script = tmp_path / "cloudflared"
    script.write_text(
        "#!/bin/sh\n"
        "echo 'INF Starting tunnel'\n"
        "echo 'INF +  https://brave-otter-demo.trycloudflare.com  +'\n"
        "exec sleep 60\n"
    )
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("CDT_CLOUDFLARED_PATH", str(script))
    return str(script)


def test_tunnel_start_stop(tmp_config_path, fake_cloudflared):
    manager = TunnelManager()

    async def scenario():
        url = await manager.start(8188)
        assert url == "https://brave-otter-demo.trycloudflare.com"
        assert manager.status()["running"] is True
        cfg = cfg_mod.load_config()
        assert cfg["master"]["host"] == url
        assert cfg["tunnel"]["url"] == url

        stopped = await manager.stop()
        assert stopped is True
        cfg = cfg_mod.load_config()
        assert cfg["master"]["host"] == ""  # restored
        assert "url" not in cfg["tunnel"]
        assert manager.status()["running"] is False

    asyncio.run(scenario())


def test_tunnel_missing_binary(tmp_config_path, monkeypatch):
    monkeypatch.delenv("CDT_CLOUDFLARED_PATH", raising=False)
    monkeypatch.setenv("PATH", "/nonexistent")
    manager = TunnelManager()
    with pytest.raises(TunnelError):
        asyncio.run(manager.start(8188))


def test_stale_state_cleared(tmp_config_path):
    cfg = cfg_mod.load_config()
    cfg["tunnel"] = {"url": "https://old.trycloudflare.com", "pid": 999999}
    cfg_mod.save_config(cfg)
    manager = TunnelManager()
    asyncio.run(manager.restore_from_config())
    cfg = cfg_mod.load_config()
    assert "pid" not in cfg["tunnel"]
    assert "url" not in cfg["tunnel"]
