"""Unit tests for the two-tier tile result cache store."""

from __future__ import annotations

import os

import numpy as np
import pytest

from comfyui_distributed_tpu.cache.store import (
    TileResultCache,
    _reset_tile_cache_for_tests,
    get_tile_cache,
    set_tile_cache,
)


@pytest.fixture(autouse=True)
def _clean_global_cache():
    _reset_tile_cache_for_tests()
    yield
    _reset_tile_cache_for_tests()


def _arr(seed=0, shape=(4, 4, 3)):
    rng = np.random.default_rng(seed)
    return rng.random(shape).astype(np.float32)


class TestRamTier:
    def test_put_get_roundtrip(self):
        cache = TileResultCache(ram_mb=1, disk_dir=None)
        a = _arr(1)
        cache.put("k1", a)
        got = cache.get("k1")
        np.testing.assert_array_equal(got, a)
        assert got.flags.writeable is False

    def test_put_copies_caller_mutation_invisible(self):
        cache = TileResultCache(ram_mb=1, disk_dir=None)
        a = _arr(1)
        cache.put("k1", a)
        a[0, 0, 0] = 99.0
        assert cache.get("k1")[0, 0, 0] != 99.0

    def test_miss(self):
        cache = TileResultCache(ram_mb=1, disk_dir=None)
        assert cache.get("absent") is None
        assert cache.stats()["misses"] == 1

    def test_lru_eviction_by_bytes(self):
        entry = _arr(0, shape=(64, 64, 3))  # 48 KiB
        budget_mb = (2 * entry.nbytes + entry.nbytes // 2) / (1024 * 1024)
        cache = TileResultCache(ram_mb=budget_mb, disk_dir=None)
        cache.put("a", _arr(1, shape=(64, 64, 3)))
        cache.put("b", _arr(2, shape=(64, 64, 3)))
        cache.get("a")  # touch: b becomes LRU
        cache.put("c", _arr(3, shape=(64, 64, 3)))
        assert cache.get("b") is None
        assert cache.get("a") is not None
        assert cache.get("c") is not None
        assert cache.stats()["evictions"] == 1

    def test_oversized_entry_skips_ram(self):
        cache = TileResultCache(ram_mb=0.00001, disk_dir=None)
        cache.put("big", _arr(1, shape=(64, 64, 3)))
        assert cache.stats()["ram_entries"] == 0


class TestDiskTier:
    def test_disk_roundtrip_and_promotion(self, tmp_path):
        d = str(tmp_path / "cache")
        a = _arr(5)
        writer = TileResultCache(ram_mb=1, disk_dir=d)
        writer.put("kd", a)
        # A fresh instance (cold RAM) must hit via disk.
        reader = TileResultCache(ram_mb=1, disk_dir=d)
        got = reader.get("kd")
        np.testing.assert_array_equal(got, a)
        stats = reader.stats()
        assert stats["hits_disk"] == 1
        # Promotion: the second get is a RAM hit.
        reader.get("kd")
        assert reader.stats()["hits_ram"] == 1

    def test_corrupt_body_is_miss_and_deleted(self, tmp_path):
        d = str(tmp_path / "cache")
        cache = TileResultCache(ram_mb=1, disk_dir=d)
        cache.put("kc", _arr(6))
        path = cache._disk_path("kc")
        blob = bytearray(open(path, "rb").read())
        blob[-1] ^= 0xFF  # flip one pixel byte
        with open(path, "wb") as fh:
            fh.write(bytes(blob))
        reader = TileResultCache(ram_mb=1, disk_dir=d)
        assert reader.get("kc") is None
        assert reader.stats()["corrupt"] == 1
        assert not os.path.exists(path)
        # Re-read is a clean miss, not another corruption event.
        assert reader.get("kc") is None
        assert reader.stats()["corrupt"] == 1

    def test_truncated_and_garbage_files_are_misses(self, tmp_path):
        d = str(tmp_path / "cache")
        cache = TileResultCache(ram_mb=1, disk_dir=d)
        cache.put("kt", _arr(7))
        path = cache._disk_path("kt")
        with open(path, "wb") as fh:
            fh.write(b"CDTC")  # truncated header
        reader = TileResultCache(ram_mb=1, disk_dir=d)
        assert reader.get("kt") is None
        garbage = os.path.join(d, "aa", "a" * 64 + ".tile")
        os.makedirs(os.path.dirname(garbage), exist_ok=True)
        with open(garbage, "wb") as fh:
            fh.write(b"not a cache entry at all")
        assert reader.get("a" * 64) is None
        assert reader.stats()["corrupt"] == 2

    def test_disk_prune_oldest_past_budget(self, tmp_path):
        d = str(tmp_path / "cache")
        entry_bytes = _arr(0, shape=(64, 64, 3)).nbytes
        cache = TileResultCache(
            ram_mb=1, disk_dir=d, disk_mb=(2.5 * entry_bytes) / (1024 * 1024)
        )
        for i, key in enumerate(["old", "mid", "new"]):
            cache.put(key, _arr(i, shape=(64, 64, 3)))
            os.utime(cache._disk_path(key), (1000 + i, 1000 + i))
        cache.put("newest", _arr(9, shape=(64, 64, 3)))
        assert not os.path.exists(cache._disk_path("old"))
        assert os.path.exists(cache._disk_path("newest"))

    def test_ram_disabled_still_serves_from_disk(self, tmp_path):
        d = str(tmp_path / "cache")
        cache = TileResultCache(ram_mb=0, disk_dir=d)
        a = _arr(8)
        cache.put("k0", a)
        np.testing.assert_array_equal(cache.get("k0"), a)
        assert cache.stats()["hits_disk"] == 1


class TestManagement:
    def test_clear_drops_both_tiers(self, tmp_path):
        d = str(tmp_path / "cache")
        cache = TileResultCache(ram_mb=1, disk_dir=d)
        cache.put("k1", _arr(1))
        cache.put("k2", _arr(2))
        dropped = cache.clear()
        assert dropped["dropped_entries"] >= 2
        assert cache.get("k1") is None
        assert cache.stats()["ram_entries"] == 0
        assert cache.stats()["disk_bytes"] == 0

    def test_stats_hit_rate(self):
        cache = TileResultCache(ram_mb=1, disk_dir=None)
        cache.put("k", _arr(1))
        cache.get("k")
        cache.get("absent")
        stats = cache.stats()
        assert stats["hit_rate"] == pytest.approx(0.5)
        assert stats["puts"] == 1

    def test_note_settled(self):
        cache = TileResultCache(ram_mb=1, disk_dir=None)
        cache.note_settled(3)
        assert cache.stats()["settled"] == 3


class TestGlobalAccessor:
    def test_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("CDT_CACHE", raising=False)
        assert get_tile_cache() is None

    def test_enabled_constructs_and_memoizes(self, monkeypatch, tmp_path):
        monkeypatch.setenv("CDT_CACHE", "1")
        monkeypatch.setenv("CDT_CACHE_DIR", str(tmp_path / "d"))
        cache = get_tile_cache()
        assert isinstance(cache, TileResultCache)
        assert get_tile_cache() is cache

    def test_set_returns_previous(self):
        mine = TileResultCache(ram_mb=1, disk_dir=None)
        prev = set_tile_cache(mine)
        assert prev is None
        assert get_tile_cache() is mine
        assert set_tile_cache(None) is mine
