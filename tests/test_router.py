"""Shard router units (scheduler/router.py): per-URL backoff, epoch-
preferred re-pointing, consistent-hash stability, and the shard map
the region routes serve."""

import pytest

from comfyui_distributed_tpu.scheduler.router import (
    EndpointRotation,
    ShardRing,
    ShardRouter,
)

pytestmark = pytest.mark.fast


class Clock:
    def __init__(self):
        self.now = 100.0

    def __call__(self):
        return self.now


def rotation(urls, clock, threshold=2, base=0.5, cap=30.0):
    return EndpointRotation(
        urls, threshold=threshold, backoff_base=base, backoff_cap=cap,
        clock=clock,
    )


def test_failure_threshold_repoints_and_backs_off_the_dead_address():
    clock = Clock()
    rot = rotation(["http://a:1", "http://b:2", "http://c:3"], clock)
    assert rot.current == "http://a:1"
    assert not rot.note_failure()  # one failure is a blip
    assert rot.note_failure()      # threshold: re-point
    assert rot.current == "http://b:2"
    # the dead address carries a backoff window
    snap = {e["url"]: e for e in rot.snapshot()}
    assert snap["http://a:1"]["backoff_remaining_s"] > 0
    assert snap["http://b:2"]["current"]


def test_rotation_skips_backed_off_addresses():
    """b dying right after a must not rotate BACK to a (still backing
    off) when a healthy c exists — the old global cursor did exactly
    that."""
    clock = Clock()
    rot = rotation(["http://a:1", "http://b:2", "http://c:3"], clock)
    rot.note_failure(); rot.note_failure()   # a -> backoff, now on b
    rot.note_failure(); rot.note_failure()   # b -> backoff
    assert rot.current == "http://c:3"


def test_all_backed_off_picks_earliest_expiry_never_stalls():
    clock = Clock()
    rot = rotation(["http://a:1", "http://b:2"], clock)
    rot.note_failure(); rot.note_failure()   # a backed off, on b
    rot.note_failure(); rot.note_failure()   # b backed off too
    # both dark: the rotation still points somewhere (earliest expiry)
    assert rot.current == "http://a:1"


def test_backoff_grows_exponentially_and_success_resets():
    clock = Clock()
    rot = rotation(["http://a:1", "http://b:2"], clock, base=1.0, cap=30.0)
    rot.note_failure(); rot.note_failure()   # a: first burst -> 1s
    first = rot._states["http://a:1"].backoff_until - clock()
    rot.note_failure(); rot.note_failure()   # b bursts; back on a
    assert rot.current == "http://a:1"
    rot.note_failure(); rot.note_failure()   # a: second burst -> 2s
    second = rot._states["http://a:1"].backoff_until - clock()
    assert second == pytest.approx(2 * first)
    # a response wipes the schedule for the answering address
    rot._idx = rot.urls.index("http://a:1")
    rot.note_success()
    state = rot._states["http://a:1"]
    assert state.bursts == 0 and state.backoff_until == 0.0


def test_repoint_prefers_highest_epoch_address():
    """Re-pointing goes to the address that last reported the highest
    fencing epoch — the promoted master — not blindly next-in-list."""
    clock = Clock()
    rot = rotation(["http://a:1", "http://b:2", "http://c:3"], clock)
    # c answered with epoch 7 at some point (e.g. a prior rotation)
    rot._states["http://c:3"].epoch = 7
    rot._states["http://b:2"].epoch = 3
    rot.note_failure(); rot.note_failure()
    assert rot.current == "http://c:3"


def test_ring_is_stable_and_reasonably_balanced():
    ring = ShardRing(["s0", "s1", "s2"], vnodes=64)
    placed = {f"job-{i}": ring.shard_for(f"job-{i}") for i in range(300)}
    # stable: same answer on a fresh ring (md5, not salted hash)
    ring2 = ShardRing(["s2", "s0", "s1"], vnodes=64)
    assert all(ring2.shard_for(k) == v for k, v in placed.items())
    # balanced-ish: every shard owns a meaningful share
    counts = {s: list(placed.values()).count(s) for s in ("s0", "s1", "s2")}
    assert all(c > 30 for c in counts.values()), counts


def test_ring_membership_change_moves_bounded_share():
    ring = ShardRing(["s0", "s1", "s2"], vnodes=64)
    before = {f"job-{i}": ring.shard_for(f"job-{i}") for i in range(300)}
    ring.remove("s2")
    moved = sum(
        1 for k, v in before.items()
        if v != "s2" and ring.shard_for(k) != v
    )
    assert moved == 0  # keys not on the removed shard never move


def test_router_spec_parsing_and_addressing():
    router = ShardRouter.from_spec(
        "http://a:1,http://a2:1; http://b:1", vnodes=16
    )
    assert router.enabled
    assert sorted(router.shards) == ["shard0", "shard1"]
    assert router.shards["shard0"].urls == ["http://a:1", "http://a2:1"]
    job = "job-abc"
    shard = router.shard_for(job)
    assert router.addresses_for(job) == ",".join(router.shards[shard].urls)
    # epoch learning surfaces in status
    router.note_epoch(shard, 5)
    router.note_epoch(shard, 3)  # monotonic
    status = router.status()
    assert status["shards"][shard]["epoch"] == 5


def test_empty_spec_is_unsharded():
    router = ShardRouter.from_spec("")
    assert not router.enabled
    assert router.status()["shards"] == {}


def test_rebalance_add_remove():
    router = ShardRouter({"shard0": ["http://a:1"]}, vnodes=8)
    router.rebalance("shard1", ["http://b:1"])
    assert "shard1" in router.shards
    assert router.ring.shards == ["shard0", "shard1"]
    router.rebalance("shard0", None)
    assert router.shard_for("anything") == "shard1"
