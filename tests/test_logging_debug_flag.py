"""Satellite fix: is_debug_enabled must not silently swallow a broken
debug-flag reader forever — it logs the failure once and backs off
exponentially instead of re-raising the same hidden error every TTL."""

import pytest

from comfyui_distributed_tpu.utils import logging as log_mod
from comfyui_distributed_tpu.utils.constants import DEBUG_FLAG_TTL_SECONDS


BASE = 1_000_000.0  # far from the module's real monotonic timestamps


@pytest.fixture(autouse=True)
def _restore_reader():
    log_mod._debug_cache.update(
        value=False, checked_at=-1e9, backoff=1.0, error_logged=False
    )
    yield
    log_mod.set_debug_flag_reader(None)
    log_mod._debug_cache.update(
        value=False, checked_at=0.0, backoff=1.0, error_logged=False
    )


def _drain_ring():
    log_mod.LOG_RING.clear()


def test_reader_failure_logged_once_and_backs_off():
    calls = []

    def broken_reader():
        calls.append(1)
        raise OSError("config unreadable")

    log_mod.set_debug_flag_reader(broken_reader)
    _drain_ring()

    now = BASE
    log_mod.is_debug_enabled(now)  # first read: fails, logs once
    assert len(calls) == 1
    failure_lines = [l for l in log_mod.LOG_RING if "debug-flag reader failed" in l]
    assert len(failure_lines) == 1
    assert "OSError" in failure_lines[0]

    # within the doubled TTL the reader is NOT retried (backoff)
    log_mod.is_debug_enabled(now + DEBUG_FLAG_TTL_SECONDS)
    assert len(calls) == 1

    # after the backoff elapses it retries — but does not log again
    log_mod.is_debug_enabled(now + 2 * DEBUG_FLAG_TTL_SECONDS + 0.1)
    assert len(calls) == 2
    failure_lines = [l for l in log_mod.LOG_RING if "debug-flag reader failed" in l]
    assert len(failure_lines) == 1


def test_backoff_is_capped():
    def broken_reader():
        raise RuntimeError("still broken")

    log_mod.set_debug_flag_reader(broken_reader)
    now = BASE
    for _ in range(20):  # escalate far past the cap
        now += 1000 * DEBUG_FLAG_TTL_SECONDS
        log_mod.is_debug_enabled(now)
    assert log_mod._debug_cache["backoff"] == log_mod._MAX_BACKOFF_MULTIPLIER


def test_recovery_resets_backoff_and_relogs_next_breakage():
    state = {"fail": True}

    def flaky_reader():
        if state["fail"]:
            raise OSError("down")
        return True

    log_mod.set_debug_flag_reader(flaky_reader)
    _drain_ring()
    now = BASE
    log_mod.is_debug_enabled(now)  # fail → backoff 2x, logged
    state["fail"] = False
    now += 2 * DEBUG_FLAG_TTL_SECONDS + 0.1
    assert log_mod.is_debug_enabled(now) is True  # recovered, value read
    assert log_mod._debug_cache["backoff"] == 1.0

    # a NEW breakage after recovery is logged again (once)
    state["fail"] = True
    now += DEBUG_FLAG_TTL_SECONDS + 0.1
    log_mod.is_debug_enabled(now)
    failure_lines = [l for l in log_mod.LOG_RING if "debug-flag reader failed" in l]
    assert len(failure_lines) == 2

    # the cached value survives the breakage (last good value wins)
    assert log_mod.is_debug_enabled(now) is True


def test_reader_value_still_hot_reloads():
    state = {"value": False}
    log_mod.set_debug_flag_reader(lambda: state["value"])
    now = BASE
    assert log_mod.is_debug_enabled(now) is False
    state["value"] = True
    assert log_mod.is_debug_enabled(now + DEBUG_FLAG_TTL_SECONDS + 0.1) is True
