"""Native data-plane: exact equivalence against the numpy reference,
with and without the compiled library."""

import numpy as np
import pytest

from comfyui_distributed_tpu import native


def test_native_lib_compiles():
    # g++ is part of the supported toolchain; if absent the fallback
    # path is exercised by the monkeypatched tests below instead.
    lib = native.get_lib()
    if lib is None:
        pytest.skip("no C++ toolchain in environment")


def _roundtrip_pair():
    rng = np.random.default_rng(0)
    u8 = rng.integers(0, 256, size=(17, 33, 3)).astype(np.uint8)
    f32 = rng.random((17, 33, 3)).astype(np.float32) * 1.2 - 0.1  # out of range
    return u8, f32


def test_conversions_match_numpy():
    u8, f32 = _roundtrip_pair()
    np.testing.assert_array_equal(
        native.u8_to_f32(u8), u8.astype(np.float32) / 255.0
    )
    np.testing.assert_array_equal(
        native.f32_to_u8(f32),
        (np.clip(f32, 0, 1) * 255.0 + 0.5).astype(np.uint8),
    )


def test_conversions_fallback_match(monkeypatch):
    monkeypatch.setattr(native, "get_lib", lambda: None)
    u8, f32 = _roundtrip_pair()
    np.testing.assert_array_equal(
        native.u8_to_f32(u8), u8.astype(np.float32) / 255.0
    )
    np.testing.assert_array_equal(
        native.f32_to_u8(f32),
        (np.clip(f32, 0, 1) * 255.0 + 0.5).astype(np.uint8),
    )


def test_feathered_blend_matches_numpy():
    rng = np.random.default_rng(1)
    canvas_native = rng.random((2, 20, 24, 3)).astype(np.float32)
    canvas_numpy = canvas_native.copy()
    tile = rng.random((2, 8, 8, 3)).astype(np.float32)
    mask = rng.random((8, 8)).astype(np.float32)
    y, x = 5, 7

    native.feathered_blend_inplace(canvas_native, tile, mask, y, x)
    m = mask[None, :, :, None]
    canvas_numpy[:, y:y+8, x:x+8, :] = (
        canvas_numpy[:, y:y+8, x:x+8, :] * (1 - m) + tile * m
    )
    np.testing.assert_allclose(canvas_native, canvas_numpy, atol=1e-6)


def test_weighted_accumulate_matches_numpy():
    rng = np.random.default_rng(2)
    canvas_a = np.zeros((1, 16, 16, 3), np.float32)
    weights_a = np.zeros((16, 16), np.float32)
    canvas_b = canvas_a.copy()
    weights_b = weights_a.copy()
    tile = rng.random((1, 8, 8, 3)).astype(np.float32)
    mask = rng.random((8, 8)).astype(np.float32)

    native.weighted_accumulate_inplace(canvas_a, weights_a, tile, mask, 4, 4)
    m = mask[None, :, :, None]
    canvas_b[:, 4:12, 4:12, :] += tile * m
    weights_b[4:12, 4:12] += mask
    np.testing.assert_allclose(canvas_a, canvas_b, atol=1e-6)
    np.testing.assert_allclose(weights_a, weights_b, atol=1e-6)


def test_content_hash_stable_and_sensitive():
    a = native.content_hash(b"hello world")
    assert a == native.content_hash(b"hello world")
    assert a != native.content_hash(b"hello worle")
    # matches the pure-python FNV-1a fallback exactly
    h = 1469598103934665603
    for byte in b"hello world":
        h = ((h ^ byte) * 1099511628211) & 0xFFFFFFFFFFFFFFFF
    assert a == h
