"""Worker lifecycle: launch command building, arg sanitization, PID
persistence, stale recovery, auto-populate, monitor helpers."""

import os
import subprocess
import sys
import time

import pytest

from comfyui_distributed_tpu.utils import config as cfg_mod
from comfyui_distributed_tpu.utils.exceptions import ProcessError
from comfyui_distributed_tpu.workers import detection
from comfyui_distributed_tpu.workers import process_manager as pm
from comfyui_distributed_tpu.workers import startup


def test_build_launch_command():
    manager = pm.WorkerProcessManager()
    cmd = manager.build_launch_command(
        {"id": "w1", "port": 8190, "extra_args": "--platform cpu"}
    )
    assert cmd[1:] == [
        "-m", "comfyui_distributed_tpu", "--port", "8190", "--worker",
        "--platform", "cpu",
    ]


def test_extra_args_sanitized():
    with pytest.raises(ProcessError):
        pm.sanitize_extra_args("--foo; rm -rf /")
    with pytest.raises(ProcessError):
        pm.sanitize_extra_args("$(evil)")
    assert pm.sanitize_extra_args('--a "b c"') == ["--a", "b c"]
    assert pm.sanitize_extra_args("") == []


def test_is_process_alive():
    assert pm.is_process_alive(os.getpid())
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    time.sleep(0.1)
    assert not pm.is_process_alive(999999)


def test_pid_persistence_and_stale_recovery(tmp_config_path):
    manager = pm.WorkerProcessManager()
    manager._persist("w1", 999999, None)  # dead pid
    assert "w1" in manager.managed_processes()
    stale = manager.clear_stale()
    assert stale == ["w1"]
    assert "w1" not in manager.managed_processes()


def test_concurrent_persist_does_not_lose_writers(tmp_config_path):
    """Config read-modify-write cycles run on executor threads; without
    the shared config lock, two concurrent _persist calls can load the
    same snapshot and the second save erases the first's entry."""
    import threading

    manager = pm.WorkerProcessManager()
    barrier = threading.Barrier(8)

    def persist(i):
        barrier.wait()
        manager._persist(f"w{i}", 100000 + i, None)
        manager.clear_launching(f"w{i}")

    threads = [threading.Thread(target=persist, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    managed = manager.managed_processes()
    assert sorted(managed) == [f"w{i}" for i in range(8)]
    assert all("launching" not in e for e in managed.values())


def test_launch_and_stop_real_process(tmp_config_path, tmp_path, monkeypatch):
    """Launch a real (sleep) process through the manager and tree-kill it."""
    monkeypatch.setenv("CDT_LOG_DIR", str(tmp_path / "logs"))
    manager = pm.WorkerProcessManager()
    monkeypatch.setattr(
        manager, "build_launch_command",
        lambda worker: [sys.executable, "-c", "import time; time.sleep(60)"],
    )
    info = manager.launch_worker({"id": "w2", "name": "w2", "port": 0})
    assert pm.is_process_alive(info["pid"])
    assert "w2" in manager.managed_processes()
    # duplicate launch refused while alive
    with pytest.raises(ProcessError):
        manager.launch_worker({"id": "w2", "name": "w2", "port": 0})
    assert manager.stop_worker("w2") is True
    time.sleep(0.2)
    assert not pm.is_process_alive(info["pid"])
    assert "w2" not in manager.managed_processes()


def test_clear_launching_marker(tmp_config_path):
    """_persist marks a fresh launch; clear_launching drops exactly
    that marker (reference /distributed/worker/clear_launching) and is
    idempotent."""
    manager = pm.WorkerProcessManager()
    manager._persist("w1", os.getpid(), None)
    assert manager.managed_processes()["w1"]["launching"] is True
    assert manager.clear_launching("w1") is True
    entry = manager.managed_processes()["w1"]
    assert "launching" not in entry
    assert entry["pid"] == os.getpid()  # rest of the record intact
    assert manager.clear_launching("w1") is False  # idempotent
    assert manager.clear_launching("missing") is False


def test_auto_populate_once(tmp_config_path):
    created = startup.auto_populate_workers()
    # 8 virtual chips, chip 0 reserved for the master
    assert [w["tpu_chips"] for w in created] == [[c] for c in range(1, 8)]
    assert all(not w["enabled"] for w in created)
    cfg = cfg_mod.load_config()
    assert len(cfg["workers"]) == 7
    assert cfg["settings"]["has_auto_populated_workers"] is True
    # second call is a no-op
    assert startup.auto_populate_workers() == []
    assert len(cfg_mod.load_config()["workers"]) == 7


def test_detection_helpers():
    assert len(detection.get_machine_id()) == 12
    assert detection.is_local_worker({"type": "local"})
    assert detection.is_local_worker({"type": "remote", "host": "127.0.0.1"})
    assert not detection.is_local_worker({"type": "remote", "host": "10.1.2.3"})
