"""Video pipeline: t2v shapes/determinism, seed-parallel over the mesh,
and the video workflow through the graph executor."""

import numpy as np

from comfyui_distributed_tpu.graph import ExecutionContext, GraphExecutor
from comfyui_distributed_tpu.models import video_pipeline as vp
from comfyui_distributed_tpu.parallel import build_mesh
from comfyui_distributed_tpu.parallel.collective import host_collect


def test_t2v_shapes_and_determinism():
    bundle = vp.load_video_pipeline("tiny-dit", seed=0)
    out = vp.t2v(bundle, "a river", frames=4, height=32, width=32, steps=2, seed=3)
    assert out.shape == (1, 4, 32, 32, 3)
    arr = np.asarray(out)
    assert np.isfinite(arr).all() and (arr >= 0).all() and (arr <= 1).all()
    again = vp.t2v(bundle, "a river", frames=4, height=32, width=32, steps=2, seed=3)
    np.testing.assert_array_equal(arr, np.asarray(again))


def test_t2v_parallel_participant_major():
    bundle = vp.load_video_pipeline("tiny-dit", seed=0)
    mesh = build_mesh({"data": 8})
    out = vp.t2v_parallel(
        bundle, mesh, "a storm", frames=4, height=32, width=32, steps=2, seed=9
    )
    vids = host_collect(out)
    assert vids.shape == (8, 4, 32, 32, 3)
    assert len({vids[i].tobytes() for i in range(8)}) == 8


def test_video_workflow_in_graph():
    prompt = {
        "1": {"class_type": "VideoCheckpointLoader", "inputs": {"ckpt_name": "tiny-dit"}},
        "2": {"class_type": "VideoCLIPTextEncode", "inputs": {"text": "waves", "clip": ["1", 1]}},
        "3": {"class_type": "VideoCLIPTextEncode", "inputs": {"text": "", "clip": ["1", 1]}},
        "4": {"class_type": "EmptyVideoLatent", "inputs": {"width": 32, "height": 32, "frames": 4}},
        "5": {"class_type": "DistributedSeed", "inputs": {"seed": 1}},
        "6": {
            "class_type": "VideoFlowSampler",
            "inputs": {
                "model": ["1", 0], "seed": ["5", 0], "steps": 2, "cfg": 2.0,
                "positive": ["2", 0], "negative": ["3", 0], "latent": ["4", 0],
            },
        },
        "7": {"class_type": "DistributedCollector", "inputs": {"images": ["6", 0]}},
        "8": {"class_type": "PreviewImage", "inputs": {"images": ["7", 0]}},
    }
    ctx = ExecutionContext(mesh=build_mesh({"data": 8}))
    outputs = GraphExecutor(ctx).execute(prompt)
    images = np.asarray(list(outputs.values())[0][0]["images"])
    # 8 participants x 4 frames, flattened to an IMAGE batch
    assert images.shape == (32, 32, 32, 3)


def test_i2v_clamps_first_frame():
    bundle = vp.load_video_pipeline("tiny-dit", seed=0)
    img = np.random.default_rng(4).random((1, 32, 32, 3)).astype(np.float32)
    out = vp.i2v(bundle, vp.jnp.asarray(img), "pan right", frames=4, steps=2, seed=1)
    assert out.shape == (1, 4, 32, 32, 3)
    arr = np.asarray(out)
    assert np.isfinite(arr).all()
    # frame 0 decodes the clamped reference latent: it must be much
    # closer to the VAE round-trip of the input than later frames are
    ref_rt = np.asarray(
        vp.decode_frames(bundle, vp.encode_frames(bundle, vp.jnp.asarray(img)[:, None]))
    )[0, 0]
    d0 = np.abs(arr[0, 0] - ref_rt).mean()
    d3 = np.abs(arr[0, 3] - ref_rt).mean()
    assert d0 < d3


def test_multihost_noop_without_config(monkeypatch):
    from comfyui_distributed_tpu.parallel import multihost

    for var in ("CDT_COORDINATOR", "CDT_NUM_PROCESSES", "CDT_PROCESS_ID", "CDT_MULTIHOST"):
        monkeypatch.delenv(var, raising=False)
    assert multihost.maybe_init_multihost() is False
    assert multihost.is_multihost() is False


def test_t2v_flops_composition():
    """video MFU numerator: scan-free composition, step-monotonic."""
    from comfyui_distributed_tpu.models import video_pipeline as vp

    bundle = vp.load_video_pipeline("tiny-dit", vae_name="tiny-video-vae-3d")
    f2 = vp.t2v_flops(bundle, frames=5, height=32, width=32, steps=2)
    assert f2 is not None and f2 > 0
    f4 = vp.t2v_flops(bundle, frames=5, height=32, width=32, steps=4)
    assert f4 > f2
