"""End-to-end tiny pipeline: prompt → latents → image, plus the
latent img2img path USDU tiles use."""

import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models import pipeline as pl


def _bundle():
    return pl.load_pipeline("tiny-unet", seed=0)


def test_txt2img_shapes_and_determinism():
    bundle = _bundle()
    img = pl.txt2img(
        bundle, "a red square", height=32, width=32, steps=3, seed=7, batch=2
    )
    assert img.shape == (2, 32, 32, 3)
    arr = np.asarray(img)
    assert np.isfinite(arr).all()
    assert (arr >= 0).all() and (arr <= 1).all()
    again = pl.txt2img(
        bundle, "a red square", height=32, width=32, steps=3, seed=7, batch=2
    )
    np.testing.assert_array_equal(arr, np.asarray(again))


def test_txt2img_seed_changes_output():
    bundle = _bundle()
    a = pl.txt2img(bundle, "x", height=32, width=32, steps=2, seed=1)
    b = pl.txt2img(bundle, "x", height=32, width=32, steps=2, seed=2)
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_img2img_latents_partial_denoise():
    bundle = _bundle()
    latents = jnp.ones((1, 8, 8, 4)) * 0.3
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    out = pl.img2img_latents(
        bundle, latents, pos, neg, steps=3, denoise=0.4, seed=0
    )
    assert out.shape == latents.shape
    assert np.isfinite(np.asarray(out)).all()
    # low denoise keeps output in the latents' neighborhood, not noise-scale
    assert float(jnp.abs(out).mean()) < 5.0


def test_dual_encoder_context_concat():
    """SDXL layout: context = concat of both encoders' penultimate
    hidden states (no zero padding), pooled from the projected second
    encoder."""
    import jax.numpy as jnp

    from comfyui_distributed_tpu.models import pipeline as pl

    bundle = pl.load_pipeline("tiny-unet-adm", seed=0)
    assert bundle.text_encoder_2 is not None
    cond = pl.encode_text_pooled(bundle, ["a castle on a hill"])
    # tiny-te-l width 64 + tiny-te-g width 96 = context 160
    assert cond.context.shape[-1] == 160
    assert cond.pooled.shape == (1, 96)
    # concat halves differ from zero-pad: second half must be nonzero
    assert float(jnp.abs(cond.context[..., 64:]).max()) > 0
