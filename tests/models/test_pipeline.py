"""End-to-end tiny pipeline: prompt → latents → image, plus the
latent img2img path USDU tiles use."""

import jax.numpy as jnp
import numpy as np

from comfyui_distributed_tpu.models import pipeline as pl


def _bundle():
    return pl.load_pipeline("tiny-unet", seed=0)


def test_txt2img_shapes_and_determinism():
    bundle = _bundle()
    img = pl.txt2img(
        bundle, "a red square", height=32, width=32, steps=3, seed=7, batch=2
    )
    assert img.shape == (2, 32, 32, 3)
    arr = np.asarray(img)
    assert np.isfinite(arr).all()
    assert (arr >= 0).all() and (arr <= 1).all()
    again = pl.txt2img(
        bundle, "a red square", height=32, width=32, steps=3, seed=7, batch=2
    )
    np.testing.assert_array_equal(arr, np.asarray(again))


def test_txt2img_seed_changes_output():
    bundle = _bundle()
    a = pl.txt2img(bundle, "x", height=32, width=32, steps=2, seed=1)
    b = pl.txt2img(bundle, "x", height=32, width=32, steps=2, seed=2)
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_img2img_latents_partial_denoise():
    bundle = _bundle()
    latents = jnp.ones((1, 8, 8, 4)) * 0.3
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    out = pl.img2img_latents(
        bundle, latents, pos, neg, steps=3, denoise=0.4, seed=0
    )
    assert out.shape == latents.shape
    assert np.isfinite(np.asarray(out)).all()
    # low denoise keeps output in the latents' neighborhood, not noise-scale
    assert float(jnp.abs(out).mean()) < 5.0


def test_dual_encoder_context_concat():
    """SDXL layout: context = concat of both encoders' penultimate
    hidden states (no zero padding), pooled from the projected second
    encoder."""
    import jax.numpy as jnp

    from comfyui_distributed_tpu.models import pipeline as pl

    bundle = pl.load_pipeline("tiny-unet-adm", seed=0)
    assert bundle.text_encoder_2 is not None
    cond = pl.encode_text_pooled(bundle, ["a castle on a hill"])
    # tiny-te-l width 64 + tiny-te-g width 96 = context 160
    assert cond.context.shape[-1] == 160
    assert cond.pooled.shape == (1, 96)
    # concat halves differ from zero-pad: second half must be nonzero
    assert float(jnp.abs(cond.context[..., 64:]).max()) > 0


def test_v_parameterization_exact_conversion():
    """tiny-unet-v shares weights with tiny-unet (identical module, same
    init seed); its model_fn must equal the exact v->eps transform of
    the raw network output: eps = x*s/(s^2+1) + v/sqrt(s^2+1)."""
    import jax

    eps_bundle = pl.load_pipeline("tiny-unet", seed=0)
    v_bundle = pl.load_pipeline("tiny-unet-v", seed=0)
    # same module tree + same init key => identical weights
    chex_eq = jax.tree_util.tree_all(
        jax.tree_util.tree_map(
            lambda a, b: bool((a == b).all()),
            eps_bundle.params["unet"], v_bundle.params["unet"],
        )
    )
    assert chex_eq, "tiny-unet-v must share tiny-unet's init weights"

    raw_fn = pl._make_model_fn(eps_bundle, eps_bundle.params)   # eps: raw net
    v_fn = pl._make_model_fn(v_bundle, v_bundle.params)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 4)), jnp.float32)
    sigma = jnp.asarray([3.0, 0.5], jnp.float32)
    ctx = jnp.asarray(rng.standard_normal((2, 7, 64)), jnp.float32)

    raw = np.asarray(raw_fn(x, sigma, ctx), np.float32)
    got = np.asarray(v_fn(x, sigma, ctx), np.float32)
    s = np.asarray(sigma, np.float32).reshape(-1, 1, 1, 1)
    want = np.asarray(x, np.float32) * (s / (s**2 + 1)) + raw / np.sqrt(s**2 + 1)
    np.testing.assert_allclose(got, want, atol=2e-2, rtol=0)  # bf16 net output


def test_v_parameterization_txt2img_runs():
    bundle = pl.load_pipeline("tiny-unet-v", seed=0)
    img = np.asarray(
        pl.txt2img(bundle, "v-pred", height=32, width=32, steps=2, seed=3)
    )
    assert img.shape == (1, 32, 32, 3)
    assert np.isfinite(img).all()
    assert (img >= 0).all() and (img <= 1).all()


def test_txt2img_flops_composition():
    """txt2img MFU numerator: scan-free composition, step-monotonic,
    heun costs its correction evals."""
    bundle = _bundle()
    f2 = pl.txt2img_flops(bundle, height=32, width=32, steps=2)
    assert f2 is not None and f2 > 0
    f4 = pl.txt2img_flops(bundle, height=32, width=32, steps=4)
    assert f4 > f2
    f2_heun = pl.txt2img_flops(bundle, height=32, width=32, steps=2, sampler="heun")
    assert f2_heun > f2
