"""LoRA mapping + application: kohya names derived from the checkpoint
schedules, exact merge math, node-level flow."""

import numpy as np
import pytest

import jax

from comfyui_distributed_tpu.models import get_config
from comfyui_distributed_tpu.models import lora as lora_mod
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models.io import flatten_params


def test_target_map_covers_attention_and_ff():
    targets = lora_mod.lora_target_map(
        get_config("sd15"), get_config("clip-l")
    )
    # canonical kohya names for SD1.5
    assert (
        "lora_unet_input_blocks_1_1_transformer_blocks_0_attn1_to_q"
        in targets
    )
    assert (
        "lora_unet_output_blocks_11_1_transformer_blocks_0_ff_net_0_proj"
        in targets
    )
    assert "lora_te_text_model_encoder_layers_0_self_attn_q_proj" in targets
    part, path = targets[
        "lora_unet_input_blocks_1_1_transformer_blocks_0_attn1_to_q"
    ]
    assert part == "unet"
    assert path.endswith("/attn1/to_q/kernel")


def _make_lora(kernel_shape, rank=4, alpha=2.0, seed=0):
    rng = np.random.default_rng(seed)
    i, o = kernel_shape
    down = rng.normal(size=(rank, i)).astype(np.float32)
    up = rng.normal(size=(o, rank)).astype(np.float32)
    return down, up, alpha


def test_apply_lora_exact_math():
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    unet_cfg = get_config("tiny-unet")
    te_cfg = get_config("tiny-te")
    targets = lora_mod.lora_target_map(unet_cfg, te_cfg)
    name = "lora_unet_input_blocks_1_1_transformer_blocks_0_attn1_to_q"
    assert name in targets
    part, path = targets[name]
    flat = flatten_params(jax.device_get(bundle.params[part]))
    kernel = np.asarray(flat[path], np.float32)
    down, up, alpha = _make_lora(kernel.shape)

    sd = {
        f"{name}.lora_down.weight": down,
        f"{name}.lora_up.weight": up,
        f"{name}.alpha": np.float32(alpha),
        "lora_unet_nonexistent_module.lora_down.weight": down,
        "lora_unet_nonexistent_module.lora_up.weight": up,
    }
    patched, unmatched = lora_mod.apply_lora(
        {"unet": bundle.params["unet"], "te": bundle.params["te"]},
        sd, unet_cfg, te_cfg, strength=0.5,
    )
    assert unmatched == ["lora_unet_nonexistent_module"]
    got = flatten_params(patched["unet"])[path]
    expect = kernel + 0.5 * (alpha / 4.0) * (down.T @ up.T)
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    # untouched layers stay identical
    other = "params/input_conv/kernel"
    np.testing.assert_array_equal(
        flatten_params(patched["unet"])[other], flat[other]
    )


def test_lora_loader_node(tmp_path, monkeypatch):
    from safetensors.numpy import save_file

    from comfyui_distributed_tpu.graph.nodes_core import LoraLoader

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    targets = lora_mod.lora_target_map(
        get_config("tiny-unet"), get_config("tiny-te")
    )
    name = next(n for n, (p, _) in targets.items() if p == "unet")
    part, path = targets[name]
    kernel = np.asarray(
        flatten_params(jax.device_get(bundle.params["unet"]))[path]
    )
    down, up, alpha = _make_lora(kernel.shape, seed=2)
    save_file(
        {
            f"{name}.lora_down.weight": down,
            f"{name}.lora_up.weight": up,
            f"{name}.alpha": np.asarray(alpha, np.float32),
        },
        str(tmp_path / "style.safetensors"),
    )
    monkeypatch.setenv("CDT_LORA_DIR", str(tmp_path))

    node = LoraLoader()
    new_model, new_clip = node.load_lora(bundle, bundle, "style", 1.0, 1.0)
    got = flatten_params(jax.device_get(new_model.params["unet"]))[path]
    assert np.abs(got - kernel).max() > 0  # patched
    # original bundle untouched (clone semantics)
    orig = flatten_params(jax.device_get(bundle.params["unet"]))[path]
    np.testing.assert_array_equal(orig, kernel)
    # MODEL output keeps its own (unpatched) te; CLIP output keeps its
    # own (unpatched) unet
    assert new_model.params["te"] is bundle.params["te"]
    assert new_clip.params["unet"] is bundle.params["unet"]


def test_sdxl_te1_te2_targets():
    """Kohya SDXL LoRAs use lora_te1_/lora_te2_ with HF naming for both
    encoders; te2 maps into the OpenCLIP-loaded flax tree."""
    targets = lora_mod.lora_target_map(
        get_config("sdxl"), get_config("clip-l-sdxl"), get_config("clip-g")
    )
    assert targets["lora_te1_text_model_encoder_layers_0_self_attn_q_proj"] == (
        "te", "params/block_0/q/kernel"
    )
    assert targets["lora_te2_text_model_encoder_layers_0_mlp_fc1"] == (
        "te2", "params/block_0/fc1/kernel"
    )
    # lora_te_ (SD1.x-style) still resolves to the primary encoder
    assert targets["lora_te_text_model_encoder_layers_0_mlp_fc2"] == (
        "te", "params/block_0/fc2/kernel"
    )


def test_apply_lora_te2_and_untouched_part_identity():
    """A te2-only LoRA patches te2, reports nothing unmatched, and
    returns the untouched unet/te trees as the same objects."""
    te2_cfg = get_config("tiny-te")
    import jax.numpy as jnp

    from comfyui_distributed_tpu.models import create_model

    te2 = create_model("tiny-te")
    te2_params = te2.init(
        jax.random.key(0), jnp.zeros((1, te2_cfg.max_length), jnp.int32)
    )
    unet_cfg = get_config("tiny-unet")
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    path = "params/block_0/q/kernel"
    kernel = np.asarray(flatten_params(jax.device_get(te2_params))[path])
    down, up, alpha = _make_lora(kernel.shape, seed=3)
    sd = {
        "lora_te2_text_model_encoder_layers_0_self_attn_q_proj"
        ".lora_down.weight": down,
        "lora_te2_text_model_encoder_layers_0_self_attn_q_proj"
        ".lora_up.weight": up,
    }
    patched, unmatched = lora_mod.apply_lora(
        {
            "unet": bundle.params["unet"],
            "te": bundle.params["te"],
            "te2": te2_params,
        },
        sd, unet_cfg, get_config("tiny-te"), te2_cfg=te2_cfg,
        strength=1.0, te_strength=0.5,
    )
    assert unmatched == []
    got = flatten_params(jax.device_get(patched["te2"]))[path]
    # no alpha key → alpha defaults to rank → scale = te_strength
    expect = kernel + 0.5 * (down.T @ up.T)
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    # untouched parts come back as the very same tree objects
    assert patched["unet"] is bundle.params["unet"]
    assert patched["te"] is bundle.params["te"]


def test_lora_loader_separate_clip_bundle(tmp_path, monkeypatch):
    """The CLIP output must be patched from the CLIP input's bundle,
    not the MODEL input's."""
    from safetensors.numpy import save_file

    from comfyui_distributed_tpu.graph.nodes_core import LoraLoader

    model_bundle = pl.load_pipeline("tiny-unet", seed=0)
    clip_bundle = pl.load_pipeline("tiny-unet", seed=7)
    path = "params/block_0/q/kernel"
    clip_kernel = np.asarray(
        flatten_params(jax.device_get(clip_bundle.params["te"]))[path]
    )
    down, up, alpha = _make_lora(clip_kernel.shape, seed=4)
    save_file(
        {
            "lora_te_text_model_encoder_layers_0_self_attn_q_proj"
            ".lora_down.weight": down,
            "lora_te_text_model_encoder_layers_0_self_attn_q_proj"
            ".lora_up.weight": up,
            "lora_te_text_model_encoder_layers_0_self_attn_q_proj"
            ".alpha": np.asarray(alpha, np.float32),
        },
        str(tmp_path / "te_only.safetensors"),
    )
    monkeypatch.setenv("CDT_LORA_DIR", str(tmp_path))
    new_model, new_clip = LoraLoader().load_lora(
        model_bundle, clip_bundle, "te_only", 1.0, 1.0
    )
    got = flatten_params(jax.device_get(new_clip.params["te"]))[path]
    expect = clip_kernel + (alpha / 4.0) * (down.T @ up.T)
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    # the MODEL input's own te is not what was patched
    assert new_model.params["te"] is model_bundle.params["te"]


def test_flux_lora_targets_and_apply():
    """Flux kohya layout: bare underscored transformer keys, CLIP
    tower as lora_te1 (part te2), T5 not a target."""
    bundle = pl.load_pipeline("tiny-flux", seed=0)
    unet_cfg = get_config("tiny-flux")
    targets = lora_mod.lora_target_map(
        unet_cfg, get_config("tiny-t5-shared"), te2_cfg=get_config("tiny-te")
    )
    assert "lora_unet_double_blocks_0_img_attn_qkv" in targets
    assert "lora_unet_single_blocks_0_linear1" in targets
    assert "lora_unet_final_layer_linear" in targets
    te1 = "lora_te1_text_model_encoder_layers_0_self_attn_q_proj"
    assert targets[te1][0] == "te2"
    assert not any(k.startswith("lora_te_") for k in targets)

    name = "lora_unet_double_blocks_0_img_attn_qkv"
    part, path = targets[name]
    flat = flatten_params(jax.device_get(bundle.params[part]))
    kernel = np.asarray(flat[path], np.float32)
    down, up, alpha = _make_lora(kernel.shape)
    sd = {
        f"{name}.lora_down.weight": down,
        f"{name}.lora_up.weight": up,
        f"{name}.alpha": np.float32(alpha),
    }
    patched, unmatched = lora_mod.apply_lora(
        {"unet": bundle.params["unet"]}, sd, unet_cfg, strength=0.5
    )
    assert unmatched == []
    got = np.asarray(flatten_params(patched["unet"])[path], np.float32)
    rank = down.shape[0]
    want = kernel + 0.5 * (alpha / rank) * (down.T @ up.T)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_merged_vs_segmented_parity():
    """The adapter plane's segmented application (adapters/segmented)
    must land on the same kernels as the merged loader — the two are
    interchangeable implementations of the same kohya math. Families
    covered per-target in tests/test_adapters.py; this pins the
    cross-module contract from the loader's side with a LoRA touching
    a Dense attention target and a proj target at once."""
    from comfyui_distributed_tpu.adapters.segmented import (
        build_operands,
        bundle_target_map,
        patch_params,
    )
    from comfyui_distributed_tpu.models.io import flatten_params

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    target_map = bundle_target_map(bundle)
    dense = "lora_unet_input_blocks_1_1_transformer_blocks_0_attn1_to_q"
    proj = "lora_unet_input_blocks_1_1_proj_in"
    sd = {}
    for i, name in enumerate((dense, proj)):
        _, (dim_in, dim_out) = target_map[name]
        down, up, alpha = _make_lora((dim_in, dim_out), seed=10 + i)
        sd[f"{name}.lora_down.weight"] = down
        sd[f"{name}.lora_up.weight"] = up
        sd[f"{name}.alpha"] = np.float32(alpha)
    merged, unmatched = lora_mod.apply_lora(
        {"unet": bundle.params["unet"]}, sd, get_config("tiny-unet"),
        strength=0.6,
    )
    assert unmatched == []
    patched = patch_params(
        bundle.params, build_operands(sd, target_map), scale=0.6
    )
    merged_flat = flatten_params(jax.device_get(merged["unet"]))
    patched_flat = flatten_params(jax.device_get(patched["unet"]))
    for name in (dense, proj):
        path = target_map[name][0][len("unet/"):]
        np.testing.assert_allclose(
            patched_flat[path], merged_flat[path], rtol=1e-5
        )


def test_lora_loader_rejects_non_unet(tmp_path):
    from safetensors.numpy import save_file

    from comfyui_distributed_tpu.graph.nodes_core import LoraLoader

    lora_path = tmp_path / "x.safetensors"
    save_file(
        {"lora_unet_foo.lora_down.weight": np.zeros((2, 2), np.float32)},
        str(lora_path),
    )
    bundle = pl.load_pipeline("tiny-dit", seed=0)
    with pytest.raises(ValueError, match="family"):
        LoraLoader().load_lora(bundle, bundle, str(lora_path))


def test_lora_loader_missing_file():
    from comfyui_distributed_tpu.graph.nodes_core import LoraLoader

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    with pytest.raises(FileNotFoundError):
        LoraLoader().load_lora(bundle, bundle, "/nonexistent/x.safetensors")
