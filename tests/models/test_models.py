"""Model zoo shape/finiteness checks on tiny configs (CPU-hermetic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.models.text_encoder import Tokenizer


def test_registry_unknown():
    with pytest.raises(KeyError):
        get_config("nope")
    with pytest.raises(KeyError):
        create_model("nope")


def test_tiny_unet_forward():
    unet = create_model("tiny-unet")
    cfg = get_config("tiny-unet")
    params = unet.init(jax.random.key(0), jnp.zeros((1, 16, 16, 4)),
                       jnp.zeros((1,)), jnp.zeros((1, 8, cfg.context_dim)))
    out = unet.apply(params, jnp.ones((2, 16, 16, 4)), jnp.array([10.0, 500.0]),
                     jnp.ones((2, 8, cfg.context_dim)))
    assert out.shape == (2, 16, 16, 4)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()
    # zero-init output conv ⇒ first forward is exactly zero
    np.testing.assert_array_equal(np.asarray(out), 0.0)


def test_tiny_unet_batch_spatial_polymorphic():
    """Same params must serve different spatial sizes (tile reuse)."""
    unet = create_model("tiny-unet")
    cfg = get_config("tiny-unet")
    params = unet.init(jax.random.key(0), jnp.zeros((1, 16, 16, 4)),
                       jnp.zeros((1,)), jnp.zeros((1, 8, cfg.context_dim)))
    out = unet.apply(params, jnp.ones((1, 32, 32, 4)), jnp.array([10.0]),
                     jnp.ones((1, 8, cfg.context_dim)))
    assert out.shape == (1, 32, 32, 4)


def test_tiny_vae_roundtrip_shapes():
    vae = create_model("tiny-vae")
    cfg = get_config("tiny-vae")
    img = jnp.ones((1, 32, 32, 3)) * 0.5
    params = vae.init(jax.random.key(0), img)
    z = vae.apply(params, img, method="encode")
    assert z.shape == (1, 32 // cfg.downscale, 32 // cfg.downscale, 4)
    out = vae.apply(params, z, method="decode")
    assert out.shape == (1, 32, 32, 3)
    arr = np.asarray(out)
    assert (arr >= 0).all() and (arr <= 1).all()


def test_tokenizer_deterministic_and_padded():
    tok = Tokenizer(max_length=16)
    a = tok.encode("a photo of a cat")
    b = tok.encode("a photo of a cat")
    np.testing.assert_array_equal(a, b)
    assert a.shape == (16,)
    assert a[0] == Tokenizer.BOS
    assert Tokenizer.EOS in a
    c = tok.encode("a photo of a dog")
    assert not np.array_equal(a, c)


def test_tiny_text_encoder():
    te = create_model("tiny-te")
    tok = Tokenizer(max_length=16)
    tokens = jnp.asarray(tok.encode_batch(["hello world", "bye"]))
    params = te.init(jax.random.key(0), tokens)
    hidden, pooled = te.apply(params, tokens)
    assert hidden.shape == (2, 16, 64)
    assert pooled.shape == (2, 64)
    assert np.isfinite(np.asarray(hidden)).all()


def test_tiny_dit_forward():
    dit = create_model("tiny-dit")
    cfg = get_config("tiny-dit")
    x = jnp.ones((1, 4, 8, 8, cfg.in_channels))
    ctx = jnp.ones((1, 8, cfg.context_dim))
    params = dit.init(jax.random.key(0), x, jnp.zeros((1,)), ctx)
    out = dit.apply(params, x, jnp.array([100.0]), ctx)
    assert out.shape == x.shape
    assert np.isfinite(np.asarray(out)).all()
    # the modulated head is timestep-sensitive (WAN head semantics)
    out2 = dit.apply(params, x, jnp.array([500.0]), ctx)
    assert np.abs(np.asarray(out) - np.asarray(out2)).max() > 0


def test_remat_parity():
    """remat=True must not change params or outputs (only memory)."""
    import dataclasses

    from comfyui_distributed_tpu.models.unet import UNet

    base_cfg = get_config("tiny-unet")
    cfg_r = dataclasses.replace(base_cfg, remat=True)
    unet_a, unet_b = UNet(base_cfg), UNet(cfg_r)
    x = jnp.ones((1, 16, 16, 4))
    t = jnp.array([7.0])
    ctx = jnp.ones((1, 8, base_cfg.context_dim))
    params = unet_a.init(jax.random.key(0), x, t, ctx)
    params_r = unet_b.init(jax.random.key(0), x, t, ctx)
    flat_a = jax.tree_util.tree_leaves(params)
    flat_b = jax.tree_util.tree_leaves(params_r)
    assert len(flat_a) == len(flat_b)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    out_a = unet_a.apply(params, x, t, ctx)
    out_b = unet_b.apply(params, x, t, ctx)
    np.testing.assert_allclose(np.asarray(out_a), np.asarray(out_b), atol=1e-6)


def test_unet_small_and_odd_latents():
    """Latents not divisible by 2^depth must round-trip the U (the
    32px-input crash found in the round-2 verify drive: 4x4 latents
    through three downsamples)."""
    import jax
    import jax.numpy as jnp

    from comfyui_distributed_tpu.models.unet import UNet, UNetConfig

    cfg = UNetConfig(
        model_channels=8,
        channel_mult=(1, 2, 4, 4),
        num_res_blocks=1,
        transformer_depth=(1, 1, 1, 0),
        context_dim=16,
        num_heads=2,
        dtype="float32",
    )
    model = UNet(cfg)
    ctx = jnp.zeros((1, 4, 16))
    t = jnp.zeros((1,))
    for size in (4, 5):
        x = jnp.zeros((1, size, size, cfg.in_channels))
        params = model.init(jax.random.key(0), x, t, ctx)
        out = model.apply(params, x, t, ctx)
        assert out.shape == x.shape


def test_tokenizer_openclip_pad_semantics():
    """OpenCLIP towers (SDXL bigG, SD2 ViT-H) pad with 0 after EOS
    (open_clip.tokenize); the CLIP-L default pads with EOS."""
    clip_l = Tokenizer(max_length=12)
    open_clip = Tokenizer(max_length=12, pad_id=0)
    a = clip_l.encode("hi")
    b = open_clip.encode("hi")
    eos_pos = int(np.argmax(a == clip_l.eos_id))
    np.testing.assert_array_equal(a[:eos_pos + 1], b[:eos_pos + 1])
    assert (a[eos_pos + 1:] == clip_l.eos_id).all()
    assert (b[eos_pos + 1:] == 0).all()


def test_final_ln_on_hidden_matches_manual_norm():
    """SD2 semantics: the penultimate context is passed through the
    model's final LayerNorm (shared params). The flag must not change
    the param tree, and the normed hidden must equal a by-hand
    LayerNorm of the un-normed hidden using final_ln's scale/bias."""
    import dataclasses

    from comfyui_distributed_tpu.models.text_encoder import (
        TextEncoder, TextEncoderConfig,
    )

    base = TextEncoderConfig(
        width=64, layers=2, heads=2, max_length=16, activation="gelu",
        penultimate_hidden=True, proj_dim=64, pad_token_id=0,
    )
    sd2 = dataclasses.replace(base, final_ln_on_hidden=True)
    tok = Tokenizer(max_length=16, pad_id=0)
    tokens = jnp.asarray(tok.encode_batch(["hello world"]))

    te_raw = TextEncoder(base)
    te_sd2 = TextEncoder(sd2)
    params = te_raw.init(jax.random.key(0), tokens)
    hidden_raw, pooled_raw = te_raw.apply(params, tokens, eos_id=tok.eos_id)
    # identical param structure: sd2 config must accept the same tree
    hidden_sd2, pooled_sd2 = te_sd2.apply(params, tokens, eos_id=tok.eos_id)

    ln = params["params"]["final_ln"]
    x = np.asarray(hidden_raw, np.float64)
    mean = x.mean(axis=-1, keepdims=True)
    var = x.var(axis=-1, keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5)
    want = want * np.asarray(ln["scale"]) + np.asarray(ln["bias"])
    np.testing.assert_allclose(np.asarray(hidden_sd2), want, atol=1e-4, rtol=0)
    # pooled path is untouched by the flag
    np.testing.assert_array_equal(np.asarray(pooled_raw), np.asarray(pooled_sd2))


def test_dual_encoder_pad_ids_differ():
    """SDXL-layout bundles tokenize per encoder: CLIP-L half pads with
    EOS, the OpenCLIP half with 0."""
    from comfyui_distributed_tpu.models import pipeline as pl

    bundle = pl.load_pipeline("tiny-unet-adm", seed=0)
    assert bundle.tokenizer.pad_id == bundle.tokenizer.eos_id
    assert bundle.tokenizer_2 is not None
    assert bundle.tokenizer_2.pad_id == 0
