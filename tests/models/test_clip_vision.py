"""CLIP vision tower + WAN i2v branch: forward shapes, schedule
round-trips, real-key pins, and the native i2v sampling path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params

pytestmark = pytest.mark.slow


def test_clip_vision_forward_tokens():
    model = create_model("tiny-clip-vision")
    cfg = get_config("tiny-clip-vision")
    img = jnp.asarray(
        np.random.default_rng(0).uniform(size=(2, cfg.image_size, cfg.image_size, 3)),
        jnp.float32,
    )
    params = model.init(jax.random.key(0), img)
    out = model.apply(params, img)
    assert out.shape == (2, cfg.tokens, cfg.width)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    # arbitrary input size is resized to the tower's native resolution
    out2 = model.apply(params, jnp.zeros((1, 64, 48, 3)))
    assert out2.shape == (1, cfg.tokens, cfg.width)


def test_clip_vision_schedule_roundtrip_exact():
    model = create_model("tiny-clip-vision")
    cfg = get_config("tiny-clip-vision")
    params = model.init(
        jax.random.key(0), jnp.zeros((1, cfg.image_size, cfg.image_size, 3))
    )
    flat = flatten_params(jax.device_get(params))
    entries = sdc.clip_vision_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, entries)
    converted, missing = sdc.convert_state_dict(state_dict, entries)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)


# Genuine key names from the public HF CLIPVisionModel layout (note the
# real `pre_layrnorm` spelling).
CLIP_VISION_KNOWN_KEYS = [
    "vision_model.embeddings.class_embedding",
    "vision_model.embeddings.patch_embedding.weight",
    "vision_model.embeddings.position_embedding.weight",
    "vision_model.pre_layrnorm.weight",
    "vision_model.encoder.layers.0.self_attn.q_proj.weight",
    "vision_model.encoder.layers.0.self_attn.out_proj.bias",
    "vision_model.encoder.layers.0.mlp.fc1.weight",
    "vision_model.encoder.layers.30.layer_norm2.weight",
]


def test_clip_vision_h_schedule_covers_real_key_names():
    cfg = get_config("clip-vision-h")
    keys = {k for k, _f, _h in sdc._expand(sdc.clip_vision_schedule(cfg))}
    missing = [k for k in CLIP_VISION_KNOWN_KEYS if k not in keys]
    assert not missing, missing
    # penultimate: the last block (31) and post LN are not in the tree
    assert not any(".layers.31." in k for k in keys)
    assert "vision_model.post_layernorm.weight" not in keys


def test_wan_i2v_schedule_roundtrip_and_keys():
    model = create_model("tiny-dit-i2v")
    cfg = get_config("tiny-dit-i2v")
    params = model.init(
        jax.random.key(0),
        jnp.zeros((1, 2, 8, 8, cfg.in_channels)),
        jnp.zeros((1,)),
        jnp.zeros((1, 8, cfg.context_dim)),
        jnp.zeros((1, 17, cfg.img_dim)),
    )
    flat = flatten_params(jax.device_get(params))
    entries = sdc.wan_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, entries)
    assert "blocks.0.cross_attn.k_img.weight" in state_dict
    assert "blocks.0.cross_attn.norm_k_img.weight" in state_dict
    assert "img_emb.proj.0.weight" in state_dict
    assert "img_emb.proj.3.weight" in state_dict
    converted, missing = sdc.convert_state_dict(state_dict, entries)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )


def test_i2v_native_path_runs():
    """End-to-end native i2v: CLIP tokens + channel-concat conditioning
    through sampling and decode."""
    from comfyui_distributed_tpu.models.video_pipeline import (
        i2v,
        load_video_pipeline,
    )

    bundle = load_video_pipeline("tiny-dit-i2v")
    assert bundle.clip_vision is not None
    assert "clip_vision" in bundle.params
    img = jnp.asarray(
        np.random.default_rng(1).uniform(size=(1, 32, 32, 3)), jnp.float32
    )
    out = i2v(bundle, img, "a rolling wave", frames=4, steps=2)
    assert out.shape[:2] == (1, 4)
    assert np.isfinite(np.asarray(out, np.float32)).all()
