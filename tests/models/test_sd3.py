"""SD3/SD3.5 family: triple-encoder conditioning, flow generation,
pre_only final block, and checkpoint-schedule round-trips.

Parity target: the reference serves SD3-class models through ComfyUI's
model zoo (CheckpointLoaderSimple on the single-file sd3*/sd3.5*
checkpoints with bundled text encoders)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params
from comfyui_distributed_tpu.models.registry import get_config

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    return pl.load_pipeline("tiny-sd3", seed=0)


def test_conditioning_layout(bundle):
    """CLIP-L ++ CLIP-G on features (zero-padded to the T5 width),
    sequence-concat with T5; pooled = pooled_l ++ pooled_g."""
    cfg = get_config("tiny-sd3")
    cond = pl.encode_text_pooled(bundle, ["a prompt"])
    clip_len = bundle.tokenizer.max_length
    t5_len = bundle.tokenizer_3.max_length
    assert cond.context.shape == (1, clip_len + t5_len, cfg.context_dim)
    assert cond.pooled.shape == (1, cfg.pooled_dim)
    # the pad region of the CLIP half is exactly zero
    l_w = get_config("tiny-te-l").width
    g_w = get_config("tiny-te-g").width
    pad = np.asarray(cond.context[:, :clip_len, l_w + g_w:])
    assert pad.shape[-1] == cfg.context_dim - l_w - g_w
    np.testing.assert_array_equal(pad, 0.0)


def test_txt2img_tiny_sd3(bundle):
    img = pl.txt2img(
        bundle, "a prompt", height=32, width=32, steps=2, cfg_scale=4.0,
        sampler="euler", seed=0,
    )
    assert img.shape == (1, 32, 32, 3)
    assert np.isfinite(np.asarray(img)).all()
    img2 = pl.txt2img(
        bundle, "a prompt", height=32, width=32, steps=2, cfg_scale=4.0,
        sampler="euler", seed=1,
    )
    assert not np.array_equal(np.asarray(img), np.asarray(img2))


def test_usdu_on_sd3(bundle):
    from comfyui_distributed_tpu.ops import upscale as up

    rng = np.random.default_rng(11)
    img = jnp.asarray(rng.random((1, 64, 64, 3)), dtype=jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    out = up.run_upscale(
        bundle, img, pos, neg, mesh=None, upscale_by=2.0, tile=64,
        padding=16, steps=2, denoise=0.4, seed=3,
    )
    assert out.shape == (1, 128, 128, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_pre_only_final_block(bundle):
    """The last joint block's context side has qkv + 2-way adaLN only
    — no proj/MLP params — and its modulation is 2*hidden wide."""
    cfg = get_config("tiny-sd3")
    flat = flatten_params(jax.device_get(bundle.params["unet"]))
    last = f"joint_blocks_{cfg.depth - 1}"
    assert f"params/{last}/ctx_attn_qkv/kernel" in flat
    assert f"params/{last}/ctx_attn_proj/kernel" not in flat
    assert f"params/{last}/ctx_mlp_fc1/kernel" not in flat
    assert flat[f"params/{last}/ctx_mod_lin/kernel"].shape == (
        cfg.width, 2 * cfg.width,
    )
    assert flat[f"params/{last}/x_mod_lin/kernel"].shape == (
        cfg.width, 6 * cfg.width,
    )


def test_sd3_schedule_roundtrip_exact(bundle):
    cfg = get_config("tiny-sd3")
    flat = flatten_params(jax.device_get(bundle.params["unet"]))
    schedule = sdc.sd3_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, schedule)
    converted, missing = sdc.convert_state_dict(state_dict, schedule)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)


@pytest.fixture(scope="module")
def bundle_x():
    """tiny MMDiT-X (SD3.5-medium layout): block 0 dual-attention,
    block 1 plain + pre_only final."""
    return pl.load_pipeline("tiny-sd35m", seed=0)


def test_mmditx_dual_attention_structure(bundle_x):
    cfg = get_config("tiny-sd35m")
    flat = flatten_params(jax.device_get(bundle_x.params["unet"]))
    # dual block: 9-way x adaLN + a second image-only attention with
    # its own qk-norm (x_block.attn2.* in the published checkpoint)
    assert flat["params/joint_blocks_0/x_mod_lin/kernel"].shape == (
        cfg.width, 9 * cfg.width,
    )
    for key in ("x2_attn_qkv", "x2_attn_proj", "x2_attn_ln_q", "x2_attn_ln_k"):
        assert any(
            k.startswith(f"params/joint_blocks_0/{key}/") for k in flat
        ), key
    # plain block: 6-way adaLN, no attn2
    assert flat["params/joint_blocks_1/x_mod_lin/kernel"].shape == (
        cfg.width, 6 * cfg.width,
    )
    assert not any("joint_blocks_1/x2_" in k for k in flat)


def test_txt2img_tiny_sd35m(bundle_x):
    img = pl.txt2img(
        bundle_x, "a prompt", height=32, width=32, steps=2, cfg_scale=4.0,
        sampler="euler", seed=0,
    )
    assert img.shape == (1, 32, 32, 3)
    assert np.isfinite(np.asarray(img)).all()


def test_sd35m_schedule_roundtrip_exact(bundle_x):
    cfg = get_config("tiny-sd35m")
    flat = flatten_params(jax.device_get(bundle_x.params["unet"]))
    schedule = sdc.sd3_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, schedule)
    converted, missing = sdc.convert_state_dict(state_dict, schedule)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)


# Genuine key names from the published SD3.5-medium (MMDiT-X) layout.
SD35M_KNOWN_KEYS = [
    "model.diffusion_model.joint_blocks.0.x_block.attn2.qkv.weight",
    "model.diffusion_model.joint_blocks.0.x_block.attn2.qkv.bias",
    "model.diffusion_model.joint_blocks.0.x_block.attn2.proj.weight",
    "model.diffusion_model.joint_blocks.0.x_block.attn2.ln_q.weight",
    "model.diffusion_model.joint_blocks.0.x_block.attn2.ln_k.weight",
    "model.diffusion_model.joint_blocks.0.x_block.attn.ln_q.weight",
    "model.diffusion_model.joint_blocks.0.x_block.adaLN_modulation.1.weight",
]


def test_sd35_medium_schedule_covers_real_keys():
    cfg = get_config("sd35-medium")
    assert cfg.depth == 24 and cfg.dual_attn_blocks == 13
    assert cfg.width == 1536 and cfg.pos_embed_max == 384
    keys = {k for k, _f, _h in sdc._expand(sdc.sd3_schedule(cfg))}
    missing = [k for k in SD35M_KNOWN_KEYS if k not in keys]
    assert not missing, missing
    # attn2 exists exactly for blocks 0..12
    assert (
        "model.diffusion_model.joint_blocks.12.x_block.attn2.qkv.weight"
        in keys
    )
    assert (
        "model.diffusion_model.joint_blocks.13.x_block.attn2.qkv.weight"
        not in keys
    )


def test_skip_layer_guidance(bundle_x):
    """SLG (reference SkipLayerGuidanceSD3): a patched bundle changes
    the output inside its percent window, leaves it bit-identical when
    the window covers no schedule sigma, and the node validates family
    and layer range."""
    from comfyui_distributed_tpu.graph.nodes_controlnet import (
        SkipLayerGuidanceSD3,
    )

    base = np.asarray(
        pl.txt2img(
            bundle_x, "p", height=32, width=32, steps=2, cfg_scale=4.0,
            seed=0,
        )
    )
    (patched,) = SkipLayerGuidanceSD3().skip_guidance(
        bundle_x, layers="0", scale=3.0, start_percent=0.0, end_percent=0.5
    )
    slg = np.asarray(
        pl.txt2img(
            patched, "p", height=32, width=32, steps=2, cfg_scale=4.0,
            seed=0,
        )
    )
    assert not np.allclose(base, slg)
    assert np.isfinite(slg).all()

    # a window past the schedule's sigmas still runs (the skip pass is
    # unconditional, the gate is arithmetic) and stays finite; exact
    # equality with the unpatched program is NOT asserted at this
    # level — two differently-fused XLA programs legitimately differ
    # in float rounding (see test_slg_gate_semantics for the exact
    # gating contract)
    (inactive,) = SkipLayerGuidanceSD3().skip_guidance(
        bundle_x, layers="0", scale=3.0, start_percent=0.99,
        end_percent=1.0,
    )
    out_inactive = np.asarray(
        pl.txt2img(
            inactive, "p", height=32, width=32, steps=2, cfg_scale=4.0,
            seed=0,
        )
    )
    np.testing.assert_allclose(base, out_inactive, atol=5e-2)

    # empty layer list / zero scale are no-op passthroughs
    (noop,) = SkipLayerGuidanceSD3().skip_guidance(bundle_x, layers="")
    assert noop is bundle_x
    (noop2,) = SkipLayerGuidanceSD3().skip_guidance(
        bundle_x, layers="0", scale=0.0
    )
    assert noop2 is bundle_x

    with pytest.raises(ValueError, match="out of range"):
        SkipLayerGuidanceSD3().skip_guidance(bundle_x, layers="99")
    with pytest.raises(ValueError, match="must be <="):
        SkipLayerGuidanceSD3().skip_guidance(
            bundle_x, layers="0", start_percent=0.5, end_percent=0.1
        )
    with pytest.raises(ValueError, match="SD3-class"):
        SkipLayerGuidanceSD3().skip_guidance(
            pl.load_pipeline("tiny-unet"), layers="0"
        )


def test_slg_gate_semantics():
    """Exact gating contract of slg_cfg_model on a toy model (eager
    arithmetic — no cross-program XLA rounding): inside the sigma
    window the correction applies, outside the result equals plain
    CFG bit-for-bit."""
    from comfyui_distributed_tpu.ops import samplers as smp

    def model(x, sigma, cond):
        return x * cond

    def skip_model(x, sigma, cond):
        return x * cond + 1.0

    x = jnp.ones((2, 4))
    # batch-major conditioning so the CFG batcher can concatenate
    cond = (jnp.full((2, 1), 2.0), jnp.full((2, 1), 0.5))
    guided = smp.slg_cfg_model(
        model, skip_model, cfg_scale=4.0, slg_scale=3.0,
        sigma_start=0.8, sigma_end=0.2,
    )
    plain = smp.cfg_model(model, 4.0)
    sig_in = jnp.full((2,), 0.5)   # inside [0.2, 0.8]
    sig_out = jnp.full((2,), 0.9)  # outside
    np.testing.assert_array_equal(
        np.asarray(guided(x, sig_out, cond)),
        np.asarray(plain(x, sig_out, cond)),
    )
    # inside: plain + slg_scale * (cond - skip) = plain + 3 * (-1)
    np.testing.assert_allclose(
        np.asarray(guided(x, sig_in, cond)),
        np.asarray(plain(x, sig_in, cond)) - 3.0,
        rtol=1e-6,
    )


def test_percent_to_sigma_families():
    from comfyui_distributed_tpu.ops import samplers as smp

    # flow: percent walks the shifted grid from sigma_max=1 to 0
    assert smp.percent_to_sigma(0.0, "flow", 3.0) == float("inf")
    assert smp.percent_to_sigma(1.0, "flow", 3.0) == 0.0
    mid = smp.percent_to_sigma(0.5, "flow", 1.0)
    assert mid == pytest.approx(0.5)
    # shift pushes the same percent to a higher sigma
    assert smp.percent_to_sigma(0.5, "flow", 3.0) > mid
    # VP: endpoints map to the table's extremes
    hi = smp.percent_to_sigma(0.001, "eps")
    lo = smp.percent_to_sigma(0.999, "eps")
    assert hi > 10 and lo < 0.1


def test_hf_projection_is_sibling_of_text_model():
    """CLIPTextModelWithProjection packs text_projection BESIDE
    text_model — a nested key would fail every real incl_clips file."""
    entries = sdc.text_encoder_schedule(
        get_config("tiny-te-g"),
        prefix="text_encoders.clip_g.transformer.text_model",
        projection_layout="linear",
    )
    keys = [sd for sd, _, _ in entries]
    assert "text_encoders.clip_g.transformer.text_projection" in keys
    assert "text_encoders.clip_g.transformer.text_model.text_projection" not in keys


def test_full_size_encoder_configs():
    """SD3 uses PROJECTED CLIP-L pooled and 77-token T5 padding."""
    assert get_config("clip-l-sd3").proj_dim == 768
    assert get_config("t5-xxl-sd3").max_length == 77


def test_load_sd3_weights_single_file(bundle):
    """A synthesized *_incl_clips-style single file (transformer + AE +
    all three encoders under text_encoders.*) maps every part."""
    unet_cfg = get_config("tiny-sd3")
    state_dict = {}
    state_dict.update(
        sdc.synthesize_state_dict(
            flatten_params(jax.device_get(bundle.params["unet"])),
            sdc.sd3_schedule(unet_cfg),
        )
    )
    state_dict.update(
        sdc.synthesize_state_dict(
            flatten_params(jax.device_get(bundle.params["vae"])),
            sdc.vae_schedule(get_config("tiny-vae-sd3")),
        )
    )
    state_dict.update(
        sdc.synthesize_state_dict(
            flatten_params(jax.device_get(bundle.params["te"])),
            sdc.text_encoder_schedule(
                get_config("tiny-te-l"),
                prefix="text_encoders.clip_l.transformer.text_model",
                projection_layout="linear",
            ),
        )
    )
    state_dict.update(
        sdc.synthesize_state_dict(
            flatten_params(jax.device_get(bundle.params["te2"])),
            sdc.text_encoder_schedule(
                get_config("tiny-te-g"),
                prefix="text_encoders.clip_g.transformer.text_model",
                projection_layout="linear",
            ),
        )
    )
    state_dict.update(
        sdc.synthesize_state_dict(
            flatten_params(jax.device_get(bundle.params["te3"])),
            sdc.t5_encoder_schedule(
                get_config("tiny-t5-sd3"),
                prefix="text_encoders.t5xxl.transformer.",
            ),
        )
    )
    templates = {
        part: bundle.params[part] for part in ("unet", "vae", "te", "te2", "te3")
    }
    out, problems = sdc.load_sd_weights(
        state_dict, unet_cfg, get_config("tiny-vae-sd3"),
        get_config("tiny-te-l"), templates,
        te2_cfg=get_config("tiny-te-g"), te3_cfg=get_config("tiny-t5-sd3"),
        family="sd3",
    )
    assert problems == []
    for part in ("unet", "vae", "te", "te2", "te3"):
        got = flatten_params(out[part])
        want = flatten_params(jax.device_get(bundle.params[part]))
        for key in want:
            np.testing.assert_array_equal(
                got[key], np.asarray(want[key]), err_msg=f"{part}:{key}"
            )
