"""Clip-skip (CLIPSetLastLayer): encoder-level skip_last semantics and
the node-level bundle patch (reference: ComfyUI's clip.clip_layer /
CLIPSetLastLayer, the classic "clip skip 2" knob)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models.registry import get_config
from comfyui_distributed_tpu.models.text_encoder import (
    TextEncoder,
    TextEncoderConfig,
)

pytestmark = pytest.mark.slow


def _enc(cfg):
    model = TextEncoder(cfg)
    tokens = jnp.asarray(
        np.array([[5, 7, 9, 2, 0, 0, 0, 0]], np.int32)
    )
    params = model.init(jax.random.key(0), tokens)
    return model, params, tokens


def test_skip_default_matches_legacy_behavior():
    """skip_last=None reproduces the configured default exactly: full
    stack for SD1-style configs, penultimate for SDXL-style."""
    cfg = TextEncoderConfig(width=32, layers=3, heads=2, max_length=8)
    model, params, tokens = _enc(cfg)
    h_none, p_none = model.apply(params, tokens)
    h_zero, p_zero = model.apply(params, tokens, skip_last=0)
    np.testing.assert_array_equal(np.asarray(h_none), np.asarray(h_zero))
    np.testing.assert_array_equal(np.asarray(p_none), np.asarray(p_zero))

    pen = dataclasses.replace(cfg, penultimate_hidden=True)
    model2, params2, _ = _enc(pen)
    h_def, _ = model2.apply(params2, tokens)
    h_one, _ = model2.apply(params2, tokens, skip_last=1)
    np.testing.assert_array_equal(np.asarray(h_def), np.asarray(h_one))


def test_skip_changes_hidden_not_pooled():
    cfg = TextEncoderConfig(width=32, layers=3, heads=2, max_length=8)
    model, params, tokens = _enc(cfg)
    h0, p0 = model.apply(params, tokens)
    h2, p2 = model.apply(params, tokens, skip_last=2)
    assert not np.array_equal(np.asarray(h0), np.asarray(h2))
    # pooled always comes from the full stack (reference semantics)
    np.testing.assert_array_equal(np.asarray(p0), np.asarray(p2))


def test_skip_zero_on_penultimate_config_uses_full_stack():
    """CLIPSetLastLayer(-1) on a penultimate-default tower forces the
    full stack, honoring the tower's LN setting: an SD2-style tower
    (final_ln_on_hidden=True) matches a non-penultimate config over
    the same params; an SDXL-style tower (False) returns the PRE-LN
    last-layer state (ComfyUI layer_norm_hidden_state=False)."""
    pen_ln = TextEncoderConfig(
        width=32, layers=3, heads=2, max_length=8,
        penultimate_hidden=True, final_ln_on_hidden=True,
    )
    model, params, tokens = _enc(pen_ln)
    h_full, _ = model.apply(params, tokens, skip_last=0)
    plain = TextEncoder(
        dataclasses.replace(
            pen_ln, penultimate_hidden=False, final_ln_on_hidden=False
        )
    )
    h_plain, _ = plain.apply(params, tokens)
    np.testing.assert_array_equal(np.asarray(h_full), np.asarray(h_plain))

    # no-LN tower: skip=0 differs from the post-LN full stack and from
    # its own penultimate default
    pen_raw = dataclasses.replace(pen_ln, final_ln_on_hidden=False)
    model2, params2, _ = _enc(pen_raw)
    h_raw, _ = model2.apply(params2, tokens, skip_last=0)
    h_def, _ = model2.apply(params2, tokens)
    assert not np.array_equal(np.asarray(h_raw), np.asarray(h_plain))
    assert not np.array_equal(np.asarray(h_raw), np.asarray(h_def))


def test_skip_too_deep_falls_back_to_last_layer():
    """ComfyUI clamps a too-deep clip_skip to the tower's LAST layer
    (skip 0), not its penultimate default — dual-tower bundles have
    different depths; a value valid for the deeper tower must not
    reject (or silently re-default) the shallower one."""
    cfg = TextEncoderConfig(width=32, layers=3, heads=2, max_length=8)
    model, params, tokens = _enc(cfg)
    h_deep, _ = model.apply(params, tokens, skip_last=3)
    h_last, _ = model.apply(params, tokens, skip_last=0)
    np.testing.assert_array_equal(np.asarray(h_deep), np.asarray(h_last))

    # penultimate tower: too-deep is LAST layer, not the penultimate
    # default (the reference's 'last', verified distinct)
    pen = dataclasses.replace(
        cfg, penultimate_hidden=True, final_ln_on_hidden=True
    )
    model2, params2, _ = _enc(pen)
    h_deep2, _ = model2.apply(params2, tokens, skip_last=5)
    h_last2, _ = model2.apply(params2, tokens, skip_last=0)
    h_def2, _ = model2.apply(params2, tokens)
    np.testing.assert_array_equal(np.asarray(h_deep2), np.asarray(h_last2))
    assert not np.array_equal(np.asarray(h_deep2), np.asarray(h_def2))

    # no-LN tower (SDXL-style): too-deep = reference 'last' = POST
    # final LN — distinct from the explicit skip 0, which is pre-LN
    raw = dataclasses.replace(pen, final_ln_on_hidden=False)
    model3, params3, _ = _enc(raw)
    h_deep3, _ = model3.apply(params3, tokens, skip_last=5)
    h_zero3, _ = model3.apply(params3, tokens, skip_last=0)
    plain3 = TextEncoder(
        dataclasses.replace(
            raw, penultimate_hidden=False, final_ln_on_hidden=False
        )
    )
    h_post3, _ = plain3.apply(params3, tokens)  # full stack post-LN
    np.testing.assert_array_equal(np.asarray(h_deep3), np.asarray(h_post3))
    assert not np.array_equal(np.asarray(h_deep3), np.asarray(h_zero3))


def test_clip_set_last_layer_node():
    from comfyui_distributed_tpu.graph.nodes_core import CLIPSetLastLayer

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    base = np.asarray(pl.encode_text(bundle, ["a prompt"]))
    (skipped,) = CLIPSetLastLayer().set_last_layer(bundle, -2)
    assert skipped.clip_skip == 1
    ctx = np.asarray(pl.encode_text(skipped, ["a prompt"]))
    assert not np.array_equal(base, ctx)
    # -1 = full stack: identical to the tiny-unet default (full-stack
    # tower)
    (full,) = CLIPSetLastLayer().set_last_layer(bundle, -1)
    np.testing.assert_array_equal(
        base, np.asarray(pl.encode_text(full, ["a prompt"]))
    )
    with pytest.raises(ValueError, match="negative"):
        CLIPSetLastLayer().set_last_layer(bundle, 1)
