"""CLIP BPE tokenizer: golden parity against transformers' reference
implementation over the SAME committed vocab files, plus roundtrip and
layout invariants. This is the guarantee that dropping in OpenAI's
real vocab.json/merges.txt yields exact CLIP tokenization."""

import gzip
import os
import shutil

import numpy as np
import pytest

ASSET_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "comfyui_distributed_tpu", "models", "assets", "clip_vocab",
)

PROMPTS = [
    "a photograph of a mountain lake at dawn",
    "A PHOTOGRAPH of a Mountain Lake at Dawn!!",
    "blurry, low quality",
    "",
    "  leading and trailing   whitespace  ",
    "hyphenated-word and under_scores and CamelCase",
    "masterpiece, best quality, 8k uhd, dslr, soft lighting, film grain",
    "it's a dog's breakfast; they're won't can't",
    "numbers 12345 and 3.14159 and v2.1",
    "unicode: café naïve über straße",
    "emoji \U0001f600 and symbols © ® ™",
    "newline\nand\ttab characters",
    "<|startoftext|> special markers <|endoftext|>",
    "a very long prompt " * 30,
    "中文字符 mixed with english",
]


@pytest.fixture(scope="module")
def bpe():
    from comfyui_distributed_tpu.models.clip_bpe import ClipBPE

    return ClipBPE(ASSET_DIR)


@pytest.fixture(scope="module")
def hf_tokenizer(tmp_path_factory):
    """transformers.CLIPTokenizer reading the same (gunzipped) files."""
    tmp = tmp_path_factory.mktemp("clip_vocab")
    for name in ("vocab.json", "merges.txt"):
        with gzip.open(os.path.join(ASSET_DIR, name + ".gz"), "rb") as src:
            with open(tmp / name, "wb") as dst:
                shutil.copyfileobj(src, dst)
    from transformers import CLIPTokenizer

    return CLIPTokenizer(str(tmp / "vocab.json"), str(tmp / "merges.txt"))


def test_vocab_layout(bpe):
    assert len(bpe.encoder) == 49408
    assert bpe.bos_id == 49406
    assert bpe.eos_id == 49407
    # first 256 entries are the byte alphabet
    from comfyui_distributed_tpu.models.clip_bpe import bytes_to_unicode

    units = list(bytes_to_unicode().values())
    for i, unit in enumerate(units):
        assert bpe.encoder[unit] == i
        assert bpe.encoder[unit + "</w>"] == 256 + i


@pytest.mark.parametrize("prompt", PROMPTS)
def test_parity_with_transformers(bpe, hf_tokenizer, prompt):
    ours = bpe.encode_text(prompt)
    theirs = hf_tokenizer(prompt, add_special_tokens=False)["input_ids"]
    assert ours == theirs, f"mismatch for {prompt!r}"


def test_padded_encode_matches_transformers(hf_tokenizer):
    from comfyui_distributed_tpu.models.text_encoder import Tokenizer

    tok = Tokenizer(max_length=77, vocab_path=ASSET_DIR)
    for prompt in PROMPTS:
        ours = tok.encode(prompt)
        theirs = hf_tokenizer(
            prompt, padding="max_length", max_length=77, truncation=True
        )["input_ids"]
        assert ours.tolist() == theirs, f"mismatch for {prompt!r}"


def test_roundtrip(bpe):
    text = "a photograph of a mountain lake at dawn"
    assert bpe.decode(bpe.encode_text(text)) == text


def test_subword_structure(bpe):
    """Real BPE property the old hash scheme lacked: unseen words
    decompose into multiple subword ids, all decodable."""
    ids = bpe.encode_text("xqzvbrella")
    assert len(ids) > 1
    assert bpe.decode(ids) == "xqzvbrella"


def test_no_collisions_distinct_words(bpe):
    a = bpe.encode_text("mountain")
    b = bpe.encode_text("fountain")
    assert a != b


def test_default_tokenizer_uses_committed_vocab():
    from comfyui_distributed_tpu.models.text_encoder import Tokenizer

    tok = Tokenizer()
    enc = tok.encode("hello world")
    assert enc.shape == (77,)
    assert enc[0] == tok.bos_id == 49406
    assert enc[-1] == tok.eos_id == 49407
    assert tok.decode(enc) == "hello world"


def test_encode_batch_deterministic():
    from comfyui_distributed_tpu.models.text_encoder import Tokenizer

    tok = Tokenizer()
    x = tok.encode_batch(["a dog", "a cat"])
    y = tok.encode_batch(["a dog", "a cat"])
    np.testing.assert_array_equal(x, y)
    assert x.shape == (2, 77)


# --- canonical-vocab gate (the fidelity the reference inherits from
# ComfyUI's bundled tokenizer) ---------------------------------------------

# Published CLIP ids: official CLIP notebook's tokenize("hello world!")
# and the transformers docs' cat/dog examples.
CANONICAL = {
    "hello world!": [49406, 3306, 1002, 256, 49407],
    "a photo of a cat": [49406, 320, 1125, 539, 320, 2368, 49407],
    "a photo of a dog": [49406, 320, 1125, 539, 320, 1929, 49407],
}


def test_canonical_ids_when_real_vocab_installed(bpe):
    """Once scripts/fetch_clip_vocab.py has installed OpenAI's table,
    the committed assets must produce the published CLIP ids exactly;
    with the prose-trained stand-in the check is skipped (and the
    loud-warning test below takes over)."""
    if not bpe.is_canonical:
        pytest.skip("stand-in vocab active (no egress on build host)")
    for prompt, want in CANONICAL.items():
        got = [bpe.bos_id] + bpe.encode_text(prompt) + [bpe.eos_id]
        assert got == want, prompt


def test_noncanonical_vocab_warns_loudly(caplog):
    """get_bpe() must flag a non-CLIP vocab — silent wrong token ids
    are the round-2 verdict's top fidelity gap."""
    import logging

    from comfyui_distributed_tpu.models import clip_bpe

    bpe = clip_bpe.ClipBPE(ASSET_DIR)
    clip_bpe._get_bpe_cached.cache_clear()
    with caplog.at_level(logging.WARNING, logger="cdt.clip_bpe"):
        clip_bpe.get_bpe(ASSET_DIR)
    if bpe.is_canonical:
        assert not caplog.records
    else:
        assert any("fetch_clip_vocab" in r.getMessage() for r in caplog.records)


def test_fetch_script_converter_reproduces_clip_layout(tmp_path):
    """convert_bpe_txt follows CLIP's SimpleTokenizer construction:
    byte units at 0-255, `</w>` variants at 256-511, merge tokens in
    file order, specials last — validated on a synthetic merge table."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fetch_clip_vocab",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            "scripts", "fetch_clip_vocab.py",
        ),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    raw = gzip.compress(
        "#version header\nh e\nhe l\nhel l\nhell o</w>\n".encode()
    )
    vocab, merges = mod.convert_bpe_txt(raw)
    from comfyui_distributed_tpu.models.clip_bpe import bytes_to_unicode

    units = list(bytes_to_unicode().values())
    assert vocab[units[0]] == 0
    assert vocab[units[0] + "</w>"] == 256
    assert vocab["he"] == 512
    assert vocab["hello</w>"] == 515
    assert vocab["<|startoftext|>"] == 516
    assert vocab["<|endoftext|>"] == 517
    assert merges == ["h e", "he l", "hel l", "hell o</w>"]

    # the written pair round-trips through ClipBPE and merges apply
    mod.write_pair(vocab, merges, str(tmp_path))
    from comfyui_distributed_tpu.models.clip_bpe import ClipBPE

    small = ClipBPE(str(tmp_path))
    assert small.encode_text("hello") == [vocab["hello</w>"]]
    # validate() rejects a non-CLIP table like this one
    assert mod.validate(str(tmp_path))
