"""CLIP BPE tokenizer: golden parity against transformers' reference
implementation over the SAME committed vocab files, plus roundtrip and
layout invariants. This is the guarantee that dropping in OpenAI's
real vocab.json/merges.txt yields exact CLIP tokenization."""

import gzip
import os
import shutil

import numpy as np
import pytest

ASSET_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "comfyui_distributed_tpu", "models", "assets", "clip_vocab",
)

PROMPTS = [
    "a photograph of a mountain lake at dawn",
    "A PHOTOGRAPH of a Mountain Lake at Dawn!!",
    "blurry, low quality",
    "",
    "  leading and trailing   whitespace  ",
    "hyphenated-word and under_scores and CamelCase",
    "masterpiece, best quality, 8k uhd, dslr, soft lighting, film grain",
    "it's a dog's breakfast; they're won't can't",
    "numbers 12345 and 3.14159 and v2.1",
    "unicode: café naïve über straße",
    "emoji \U0001f600 and symbols © ® ™",
    "newline\nand\ttab characters",
    "<|startoftext|> special markers <|endoftext|>",
    "a very long prompt " * 30,
    "中文字符 mixed with english",
]


@pytest.fixture(scope="module")
def bpe():
    from comfyui_distributed_tpu.models.clip_bpe import ClipBPE

    return ClipBPE(ASSET_DIR)


@pytest.fixture(scope="module")
def hf_tokenizer(tmp_path_factory):
    """transformers.CLIPTokenizer reading the same (gunzipped) files."""
    tmp = tmp_path_factory.mktemp("clip_vocab")
    for name in ("vocab.json", "merges.txt"):
        with gzip.open(os.path.join(ASSET_DIR, name + ".gz"), "rb") as src:
            with open(tmp / name, "wb") as dst:
                shutil.copyfileobj(src, dst)
    from transformers import CLIPTokenizer

    return CLIPTokenizer(str(tmp / "vocab.json"), str(tmp / "merges.txt"))


def test_vocab_layout(bpe):
    assert len(bpe.encoder) == 49408
    assert bpe.bos_id == 49406
    assert bpe.eos_id == 49407
    # first 256 entries are the byte alphabet
    from comfyui_distributed_tpu.models.clip_bpe import bytes_to_unicode

    units = list(bytes_to_unicode().values())
    for i, unit in enumerate(units):
        assert bpe.encoder[unit] == i
        assert bpe.encoder[unit + "</w>"] == 256 + i


@pytest.mark.parametrize("prompt", PROMPTS)
def test_parity_with_transformers(bpe, hf_tokenizer, prompt):
    ours = bpe.encode_text(prompt)
    theirs = hf_tokenizer(prompt, add_special_tokens=False)["input_ids"]
    assert ours == theirs, f"mismatch for {prompt!r}"


def test_padded_encode_matches_transformers(hf_tokenizer):
    from comfyui_distributed_tpu.models.text_encoder import Tokenizer

    tok = Tokenizer(max_length=77, vocab_path=ASSET_DIR)
    for prompt in PROMPTS:
        ours = tok.encode(prompt)
        theirs = hf_tokenizer(
            prompt, padding="max_length", max_length=77, truncation=True
        )["input_ids"]
        assert ours.tolist() == theirs, f"mismatch for {prompt!r}"


def test_roundtrip(bpe):
    text = "a photograph of a mountain lake at dawn"
    assert bpe.decode(bpe.encode_text(text)) == text


def test_subword_structure(bpe):
    """Real BPE property the old hash scheme lacked: unseen words
    decompose into multiple subword ids, all decodable."""
    ids = bpe.encode_text("xqzvbrella")
    assert len(ids) > 1
    assert bpe.decode(ids) == "xqzvbrella"


def test_no_collisions_distinct_words(bpe):
    a = bpe.encode_text("mountain")
    b = bpe.encode_text("fountain")
    assert a != b


def test_default_tokenizer_uses_committed_vocab():
    from comfyui_distributed_tpu.models.text_encoder import Tokenizer

    tok = Tokenizer()
    enc = tok.encode("hello world")
    assert enc.shape == (77,)
    assert enc[0] == tok.bos_id == 49406
    assert enc[-1] == tok.eos_id == 49407
    assert tok.decode(enc) == "hello world"


def test_encode_batch_deterministic():
    from comfyui_distributed_tpu.models.text_encoder import Tokenizer

    tok = Tokenizer()
    x = tok.encode_batch(["a dog", "a cat"])
    y = tok.encode_batch(["a dog", "a cat"])
    np.testing.assert_array_equal(x, y)
    assert x.shape == (2, 77)
