"""Standalone-component loaders (pipeline.load_unet / load_clip): the
separate-file distribution format real Flux/SD3 stacks use — diffusion
transformer, text encoders, and VAE each in their own file."""

import jax
import numpy as np
import pytest

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params
from comfyui_distributed_tpu.models.registry import get_config


# --- load_clip layouts ---------------------------------------------------

def test_clip_sd_single_tower():
    c = pl.load_clip(["tiny-te"], layout="sd")
    cond = pl.encode_text_pooled(c, ["hello"])
    w = get_config("tiny-te").width
    assert cond.context.shape[-1] == w
    assert cond.pooled.shape[-1] == w
    assert c.te_name == "tiny-te"


def test_clip_sdxl_concat_layout():
    c = pl.load_clip(["tiny-te-l", "tiny-te-g"], layout="sdxl")
    cond = pl.encode_text_pooled(c, ["hello"])
    dl = get_config("tiny-te-l").width
    dg = get_config("tiny-te-g").width
    assert cond.context.shape[-1] == dl + dg
    # pooled comes from the projected G tower
    assert cond.pooled.shape[-1] == get_config("tiny-te-g").proj_dim


def test_clip_sdxl_order_sniffed_by_width():
    """ComfyUI-ported workflows pass L/G in either order; the wider
    tower (G) is identified by width and takes the te2 role."""
    a = pl.load_clip(["tiny-te-l", "tiny-te-g"], layout="sdxl")
    b = pl.load_clip(["tiny-te-g", "tiny-te-l"], layout="sdxl")
    assert a.te_name == b.te_name == "tiny-te-l"
    assert a.te2_name == b.te2_name == "tiny-te-g"
    c = pl.load_clip(["tiny-te-g", "tiny-te-l", "tiny-t5-sd3"], layout="sd3")
    assert c.te_name == "tiny-te-l" and c.te2_name == "tiny-te-g"


def test_clip_flux_order_sniffed():
    """T5 and CLIP are identified by family, so either argument order
    produces the same bundle layout (te = T5 hidden source)."""
    a = pl.load_clip(["tiny-t5-shared", "tiny-te"], layout="flux")
    b = pl.load_clip(["tiny-te", "tiny-t5-shared"], layout="flux")
    assert a.te_name == b.te_name == "tiny-t5-shared"
    assert a.te2_name == b.te2_name == "tiny-te"
    cond = pl.encode_text_pooled(a, ["hello"])
    assert cond.context.shape[-1] == get_config("tiny-t5-shared").d_model


def test_clip_sd3_with_and_without_t5():
    full = pl.load_clip(
        ["tiny-te-l", "tiny-te-g", "tiny-t5-sd3"], layout="sd3"
    )
    cond_full = pl.encode_text_pooled(full, ["hello"])
    dual = pl.load_clip(["tiny-te-l", "tiny-te-g"], layout="sd3")
    cond_dual = pl.encode_text_pooled(dual, ["hello"])
    # T5-less mode keeps the CLIP sequence only (no T5 seq concat)
    assert cond_dual.context.shape[1] < cond_full.context.shape[1]
    # both pad the feature axis to the same backbone width
    assert cond_dual.context.shape[-1] == cond_full.context.shape[-1]
    # pooled = L ++ G either way
    np.testing.assert_array_equal(
        cond_full.pooled.shape, cond_dual.pooled.shape
    )


def test_clip_layout_validation():
    with pytest.raises(ValueError, match="unknown CLIP layout"):
        pl.load_clip(["tiny-te"], layout="nope")
    with pytest.raises(ValueError, match="encoder name"):
        pl.load_clip(["tiny-te"], layout="sdxl")
    with pytest.raises(ValueError, match="CLIP-family encoders only"):
        pl.load_clip(["tiny-t5-shared", "tiny-te-g"], layout="sdxl")
    with pytest.raises(ValueError, match="one T5-family and one CLIP"):
        pl.load_clip(["tiny-te-l", "tiny-te-g"], layout="flux")


def test_clip_loads_separate_file_weights(tmp_path, monkeypatch):
    """A CLIP encoder file under the encoder's registry name feeds the
    bundle (the clip_l.safetensors distribution format)."""
    from safetensors.numpy import save_file

    cfg = get_config("tiny-te")
    from comfyui_distributed_tpu.models.registry import create_model
    import jax.numpy as jnp

    te = create_model("tiny-te")
    p = te.init(jax.random.key(9), jnp.zeros((1, cfg.max_length), jnp.int32))
    # the standalone clip_l.safetensors layout: bare text_model.* keys
    synth = sdc.synthesize_state_dict(
        flatten_params(jax.device_get(p)),
        sdc.text_encoder_schedule(cfg, prefix="text_model"),
    )
    rng = np.random.default_rng(3)
    synth = {
        k: (v + rng.normal(0, 0.01, v.shape)).astype(np.float32)
        for k, v in synth.items()
    }
    save_file(synth, str(tmp_path / "tiny-te.safetensors"))
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))

    c = pl.load_clip(["tiny-te"], layout="sd")
    got = flatten_params(jax.device_get(c.params["te"]))
    key = "params/token_embedding/embedding"
    expect = synth["text_model.embeddings.token_embedding.weight"]
    np.testing.assert_allclose(got[key], expect, rtol=1e-6)


# --- load_unet -----------------------------------------------------------

def test_unet_only_bundle_geometry():
    b = pl.load_unet("tiny-flux")
    assert b.vae is None and b.text_encoder is None
    assert b.latent_channels == get_config("tiny-vae-flux").latent_channels
    assert b.latent_scale == get_config("tiny-vae-flux").downscale
    assert set(b.params) == {"unet"}


def test_unet_rejects_non_diffusion_names():
    with pytest.raises(ValueError, match="not an image diffusion"):
        pl.load_unet("tiny-te")


@pytest.mark.parametrize("prefixed", [False, True])
def test_unet_reads_bare_and_nested_diffusion_files(
    tmp_path, monkeypatch, prefixed
):
    """load_diffusion_weights maps both published bare-key diffusion
    files and model.diffusion_model.-nested repacks onto the backbone
    tree (here: the flux schedule, whose published files are bare)."""
    from safetensors.numpy import save_file
    import jax.numpy as jnp

    cfg = get_config("tiny-flux")
    init = pl.load_unet("tiny-flux", seed=1)
    synth = sdc.synthesize_state_dict(
        flatten_params(jax.device_get(init.params["unet"])),
        sdc.flux_schedule(cfg),
    )
    rng = np.random.default_rng(5)
    synth = {
        k: (v + rng.normal(0, 0.01, v.shape)).astype(np.float32)
        for k, v in synth.items()
    }
    if prefixed:
        synth = {f"model.diffusion_model.{k}": v for k, v in synth.items()}
    save_file(synth, str(tmp_path / "tiny-flux.safetensors"))
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))

    b = pl.load_unet("tiny-flux")
    got = flatten_params(jax.device_get(b.params["unet"]))
    key = "params/img_in/kernel"
    src = "img_in.weight" if not prefixed else (
        "model.diffusion_model.img_in.weight"
    )
    np.testing.assert_allclose(
        got[key], np.transpose(synth[src], (1, 0)), rtol=1e-6
    )


def test_unet_reads_bare_sd_unet_file(tmp_path, monkeypatch):
    """Extracted SD UNets ship bare keys (no model.diffusion_model.);
    the loader re-prefixes them onto the single-file schedule."""
    from safetensors.numpy import save_file

    cfg = get_config("tiny-unet")
    init = pl.load_unet("tiny-unet", seed=1)
    synth = sdc.synthesize_state_dict(
        flatten_params(jax.device_get(init.params["unet"])),
        sdc.unet_schedule(cfg),
    )
    prefix = "model.diffusion_model."
    bare = {k[len(prefix):]: v for k, v in synth.items()}
    rng = np.random.default_rng(6)
    bare = {
        k: (v + rng.normal(0, 0.01, v.shape)).astype(np.float32)
        for k, v in bare.items()
    }
    save_file(bare, str(tmp_path / "tiny-unet.safetensors"))
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))

    b = pl.load_unet("tiny-unet")
    got = flatten_params(jax.device_get(b.params["unet"]))
    expect = sdc._transform(bare["input_blocks.0.0.weight"], "conv")
    np.testing.assert_allclose(
        got["params/input_conv/kernel"], expect, rtol=1e-6
    )
