"""Separate-file text encoders (the real Flux/SD3 distribution
format; what ComfyUI's CLIPLoader family consumes): standalone HF
clip_l/clip_g layouts and t5xxl files resolve per encoder name and
override the bundle's weights."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params
from comfyui_distributed_tpu.models.registry import create_model, get_config

pytestmark = pytest.mark.slow


def _save(path, state_dict):
    import safetensors.numpy as st

    st.save_file(
        {k: np.ascontiguousarray(v) for k, v in state_dict.items()},
        str(path),
    )


def _donor_te(name, seed):
    cfg = get_config(name)
    model = create_model(name)
    params = model.init(
        jax.random.key(seed), jnp.zeros((1, cfg.max_length), jnp.int32)
    )
    return cfg, flatten_params(jax.device_get(params)), params


def test_load_clip_te_weights_hf_sibling_projection():
    """clip_g-style standalone file: bare text_model.* keys + root
    text_projection.weight (nn.Linear packing) round-trips exactly."""
    cfg, flat, params = _donor_te("tiny-te-g", seed=5)
    state_dict = sdc.synthesize_state_dict(
        flat,
        sdc.text_encoder_schedule(
            cfg, prefix="text_model", projection_layout="linear"
        ),
    )
    assert "text_projection.weight" in state_dict  # root-level sibling
    _cfg2, _flat2, template = _donor_te("tiny-te-g", seed=0)
    out, problems = sdc.load_clip_te_weights(state_dict, cfg, template)
    assert problems == []
    got = flatten_params(jax.device_get(out))
    for key in flat:
        np.testing.assert_array_equal(got[key], flat[key], err_msg=key)


def test_load_clip_te_weights_rejects_unknown_layout():
    cfg, _flat, template = _donor_te("tiny-te", seed=0)
    with pytest.raises(ValueError, match="unrecognized standalone CLIP"):
        sdc.load_clip_te_weights({"model.blocks.0.w": np.zeros(2)}, cfg, template)


def test_checkpoint_bundled_te_beats_standalone_file(tmp_path, monkeypatch):
    """A fine-tuned checkpoint's own text encoder must NOT be
    clobbered by a same-named standalone encoder file in the same
    directory (the base-CLIP-overwrites-finetune failure mode)."""
    donor = pl.load_pipeline("tiny-unet", seed=21)
    cfg_u = get_config("tiny-unet")
    full_sd = {}
    full_sd.update(
        sdc.synthesize_state_dict(
            flatten_params(jax.device_get(donor.params["unet"])),
            sdc.unet_schedule(cfg_u),
        )
    )
    full_sd.update(
        sdc.synthesize_state_dict(
            flatten_params(jax.device_get(donor.params["vae"])),
            sdc.vae_schedule(get_config("tiny-vae")),
        )
    )
    te_flat = flatten_params(jax.device_get(donor.params["te"]))
    full_sd.update(
        sdc.synthesize_state_dict(
            te_flat,
            sdc.text_encoder_schedule(
                get_config("tiny-te"),
                prefix="cond_stage_model.transformer.text_model",
            ),
        )
    )
    _save(tmp_path / "tiny-unet.safetensors", full_sd)

    # a DIFFERENT standalone encoder under the te's registry name
    cfg_te, other_flat, _ = _donor_te("tiny-te", seed=77)
    _save(
        tmp_path / "tiny-te.safetensors",
        sdc.synthesize_state_dict(
            other_flat,
            sdc.text_encoder_schedule(
                cfg_te, prefix="text_model", projection_layout="linear"
            ),
        ),
    )
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    got = flatten_params(jax.device_get(bundle.params["te"]))
    for key in te_flat:
        np.testing.assert_array_equal(got[key], te_flat[key], err_msg=key)


def test_flux_part_detection_uses_mmdit_layout(tmp_path, monkeypatch):
    """For the mmdit (Flux) family te is the T5 and te2 the CLIP — the
    checkpoint-supplied detection must mirror load_flux_weights' own
    sniffing, not the SD-layout prefixes: a flux checkpoint bundling
    the T5 keeps it against a same-named standalone file, while the
    absent CLIP tower fills from its standalone file."""
    cfg_t5, ckpt_t5_flat, _ = _donor_te("tiny-t5-shared", seed=31)
    _save(
        tmp_path / "tiny-flux.safetensors",
        sdc.synthesize_state_dict(
            ckpt_t5_flat, sdc.t5_encoder_schedule(cfg_t5)
        ),
    )
    _cfg2, other_t5_flat, _ = _donor_te("tiny-t5-shared", seed=32)
    _save(
        tmp_path / "tiny-t5-shared.safetensors",
        sdc.synthesize_state_dict(
            other_t5_flat, sdc.t5_encoder_schedule(cfg_t5)
        ),
    )
    cfg_clip, clip_flat, _ = _donor_te("tiny-te", seed=33)
    _save(
        tmp_path / "tiny-te.safetensors",
        sdc.synthesize_state_dict(
            clip_flat,
            sdc.text_encoder_schedule(
                cfg_clip, prefix="text_model", projection_layout="linear"
            ),
        ),
    )
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))
    bundle = pl.load_pipeline("tiny-flux", seed=0)
    got_te = flatten_params(jax.device_get(bundle.params["te"]))
    for key in ckpt_t5_flat:  # checkpoint's T5 wins over standalone
        np.testing.assert_array_equal(
            got_te[key], ckpt_t5_flat[key], err_msg=key
        )
    got_te2 = flatten_params(jax.device_get(bundle.params["te2"]))
    for key in clip_flat:  # absent CLIP fills from its standalone file
        np.testing.assert_array_equal(
            got_te2[key], clip_flat[key], err_msg=key
        )


def test_load_pipeline_resolves_separate_te_files(tmp_path, monkeypatch):
    """CDT_CHECKPOINT_DIR holding per-encoder files (tiny-te-l /
    tiny-te-g / tiny-t5-sd3 stems) loads them into an SD3 bundle —
    end to end through load_pipeline."""
    # donor weights, saved in the published standalone layouts
    cfg_l, flat_l, _ = _donor_te("tiny-te-l", seed=11)
    _save(
        tmp_path / "tiny-te-l.safetensors",
        sdc.synthesize_state_dict(
            flat_l,
            sdc.text_encoder_schedule(
                cfg_l, prefix="text_model", projection_layout="linear"
            ),
        ),
    )
    cfg_t5, flat_t5, _ = _donor_te("tiny-t5-sd3", seed=13)
    _save(
        tmp_path / "tiny-t5-sd3.safetensors",
        sdc.synthesize_state_dict(
            flat_t5, sdc.t5_encoder_schedule(cfg_t5)
        ),
    )
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))
    bundle = pl.load_pipeline("tiny-sd3", seed=0)
    got_l = flatten_params(jax.device_get(bundle.params["te"]))
    for key in flat_l:
        np.testing.assert_array_equal(got_l[key], flat_l[key], err_msg=key)
    got_t5 = flatten_params(jax.device_get(bundle.params["te3"]))
    for key in flat_t5:
        np.testing.assert_array_equal(got_t5[key], flat_t5[key], err_msg=key)
    # te2 had no file: stays at its deterministic init
    ref = pl.load_pipeline("tiny-sd3", seed=0)  # monkeypatched dir, no te2 file
    np.testing.assert_array_equal(
        np.asarray(
            flatten_params(jax.device_get(bundle.params["te2"]))[
                "params/token_embedding/embedding"
            ]
        ),
        np.asarray(
            flatten_params(jax.device_get(ref.params["te2"]))[
                "params/token_embedding/embedding"
            ]
        ),
    )
