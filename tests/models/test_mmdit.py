"""Flux-class MMDiT family: flow schedule, conditioning layout,
end-to-end tiny generation, and checkpoint-schedule round-trips.

Parity target: the reference serves Flux models through ComfyUI's
model zoo (UNETLoader + DualCLIPLoader; its conditioning utilities
special-case Flux reference latents — reference utils/usdu_utils.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params
from comfyui_distributed_tpu.models.registry import get_config
from comfyui_distributed_tpu.ops import samplers as smp

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    return pl.load_pipeline("tiny-flux", seed=0)


def test_flow_sigma_schedule_properties():
    s = np.asarray(smp.get_flow_sigmas(4))
    assert s.shape == (5,)
    assert s[0] == pytest.approx(1.0)  # full denoise starts at pure noise
    assert s[-1] == 0.0
    assert np.all(np.diff(s) < 0)
    # shift pushes mass toward high sigma
    shifted = np.asarray(smp.get_flow_sigmas(4, shift=3.0))
    assert shifted[2] > np.asarray(smp.get_flow_sigmas(4, shift=1.0))[2]
    # denoise truncation starts near the denoise fraction (USDU parity)
    s2 = np.asarray(smp.get_flow_sigmas(4, denoise=0.5, shift=1.0))
    assert s2.shape == (5,)
    assert s2[0] == pytest.approx(0.5, abs=0.13)


def test_model_sigmas_dispatch():
    flow = smp.get_model_sigmas("flow", "simple", 4, flow_shift=1.0)
    np.testing.assert_allclose(
        np.asarray(flow), np.asarray(smp.get_flow_sigmas(4, shift=1.0))
    )
    vp = smp.get_model_sigmas("eps", "karras", 4)
    np.testing.assert_allclose(
        np.asarray(vp), np.asarray(smp.get_sigmas("karras", 4))
    )


def test_flow_scheduler_knob_shapes_spacing():
    """scheduler='beta'/'karras' on a flow model must shape the sigma
    grid (ADVICE r4: the reference computes scheduler spacing through
    the model's sampling object for flow families too), not be silently
    ignored."""
    simple = np.asarray(smp.get_model_sigmas("flow", "simple", 8, flow_shift=3.0))
    # sgm_uniform is excluded from the inequality check: uniform index
    # spacing over the flow table IS uniform t through the shift map,
    # so it legitimately coincides with the simple grid
    for name in ("karras", "exponential", "beta", "kl_optimal", "sgm_uniform"):
        s = np.asarray(smp.get_model_sigmas("flow", name, 8, flow_shift=3.0))
        assert s.shape == simple.shape
        assert s[-1] == 0.0
        assert np.all(np.diff(s) < 0), name
        assert s[0] <= 1.0 + 1e-6  # flow sigmas live in [0, 1]
        if name != "sgm_uniform":
            assert not np.allclose(s, simple), name
    # shift still matters under a non-default scheduler
    a = np.asarray(smp.get_model_sigmas("flow", "karras", 8, flow_shift=1.0))
    b = np.asarray(smp.get_model_sigmas("flow", "karras", 8, flow_shift=3.0))
    assert not np.allclose(a, b)
    # denoise truncation behaves like the VP path
    t = np.asarray(
        smp.get_model_sigmas("flow", "karras", 4, denoise=0.5, flow_shift=1.0)
    )
    assert t.shape == (5,) and t[0] < 0.75


def test_noise_latents_interpolates_for_flow():
    z = jnp.ones((1, 2, 2, 1))
    n = jnp.zeros_like(z)
    s = jnp.float32(0.25)
    np.testing.assert_allclose(
        np.asarray(smp.noise_latents("flow", z, n, s)), 0.75
    )
    np.testing.assert_allclose(
        np.asarray(smp.noise_latents("eps", z, n, s)), 1.0
    )


def test_bundle_layout(bundle):
    """Flux conditioning: T5 hidden context + CLIP pooled vector."""
    assert bundle.latent_channels == 16
    cond = pl.encode_text_pooled(bundle, ["a prompt"])
    cfg = get_config("tiny-flux")
    assert cond.context.shape[-1] == cfg.context_dim
    assert cond.pooled is not None
    assert cond.pooled.shape[-1] == cfg.vec_dim


def test_txt2img_tiny_flux(bundle):
    img = pl.txt2img(
        bundle, "a prompt", height=32, width=32, steps=2, cfg_scale=1.0,
        sampler="euler", seed=0,
    )
    assert img.shape == (1, 32, 32, 3)
    assert np.isfinite(np.asarray(img)).all()
    img2 = pl.txt2img(
        bundle, "a prompt", height=32, width=32, steps=2, cfg_scale=1.0,
        sampler="euler", seed=1,
    )
    assert not np.array_equal(np.asarray(img), np.asarray(img2))


def test_flow_sampler_guard(bundle):
    """euler_ancestral routes to the RF renoise rule; the other
    stochastic samplers' VE renoising is rejected for flow models."""
    img = pl.txt2img(
        bundle, "p", height=32, width=32, steps=2, cfg_scale=1.0,
        sampler="euler_ancestral", seed=0,
    )
    assert np.isfinite(np.asarray(img)).all()
    with pytest.raises(ValueError, match="rectified-flow"):
        pl.txt2img(
            bundle, "p", height=32, width=32, steps=2, cfg_scale=1.0,
            sampler="dpmpp_sde", seed=0,
        )


def test_flux_guidance_conditioning(bundle):
    """The FluxGuidance knob reaches the distilled-guidance embedding:
    different scales produce different predictions."""
    import dataclasses

    cond = pl.encode_text_pooled(bundle, ["p"])
    model_fn = pl._make_model_fn(bundle, bundle.params)
    z = jnp.full((1, 4, 4, 16), 0.1)
    s = jnp.full((1,), 0.5)
    low = model_fn(z, s, dataclasses.replace(cond, guidance=1.0))
    high = model_fn(z, s, dataclasses.replace(cond, guidance=4.0))
    default = model_fn(z, s, cond)
    assert not np.allclose(np.asarray(low), np.asarray(high))
    assert np.isfinite(np.asarray(default)).all()


def test_flux_rejects_controlnet(bundle):
    z = jnp.zeros((1, 4, 4, 16))
    t = jnp.zeros((1,))
    ctx = jnp.zeros((1, 4, 64))
    with pytest.raises(ValueError, match="ControlNet"):
        bundle.unet.apply(
            bundle.params["unet"], z, t, ctx, control=jnp.zeros((1, 4, 4, 16))
        )


def test_ksampler_rebuilds_latents_for_flux(bundle):
    """EmptyLatentImage emits nominal 8x 4-channel latents; KSampler
    must rebuild them to the bundle's actual latent geometry (Flux:
    16 channels) instead of feeding 4ch latents into img_in."""
    from comfyui_distributed_tpu.graph.nodes_core import KSampler

    latent = {
        "samples": jnp.zeros((1, 4, 4, 4)), "width": 32, "height": 32,
        "empty": True,
    }
    pos = pl.encode_text_pooled(bundle, ["p"])
    neg = pl.encode_text_pooled(bundle, [""])
    (out,) = KSampler().sample(
        bundle, 0, 2, 1.0, "euler", "simple", pos, neg, latent
    )
    lh = 32 // bundle.latent_scale
    assert out["samples"].shape == (1, lh, lh, bundle.latent_channels)


def test_usdu_on_flux(bundle):
    """The tile re-diffusion core runs the flow family end to end
    (interpolation noising + flow sigmas inside the tile scan)."""
    from comfyui_distributed_tpu.ops import upscale as up

    rng = np.random.default_rng(5)
    img = jnp.asarray(rng.random((1, 64, 64, 3)), dtype=jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    out = up.run_upscale(
        bundle, img, pos, neg, mesh=None, upscale_by=2.0, tile=64,
        padding=16, steps=2, denoise=0.4, seed=3,
    )
    assert out.shape == (1, 128, 128, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_reference_latents_condition_the_output(bundle):
    """Flux-Kontext: reference latents join the image token stream and
    change the prediction; output shape stays the main image's."""
    import dataclasses

    cond = pl.encode_text_pooled(bundle, ["p"])
    model_fn = pl._make_model_fn(bundle, bundle.params)
    z = jnp.full((1, 4, 4, 16), 0.1)
    s = jnp.full((1,), 0.5)
    base = model_fn(z, s, cond)
    ref = jnp.linspace(0, 1, 4 * 4 * 16).reshape(1, 4, 4, 16)
    with_ref = model_fn(
        z, s, dataclasses.replace(cond, reference_latents=[ref])
    )
    assert with_ref.shape == base.shape
    assert not np.allclose(np.asarray(base), np.asarray(with_ref))
    # a second, different reference shifts it again (distinct rope ids)
    ref2 = jnp.flip(ref, axis=1)
    with_two = model_fn(
        z, s, dataclasses.replace(cond, reference_latents=[ref, ref2])
    )
    assert not np.allclose(np.asarray(with_ref), np.asarray(with_two))
    # odd-sized reference grids edge-pad to the patch multiple
    odd = jnp.ones((1, 5, 3, 16))
    out_odd = model_fn(
        z, s, dataclasses.replace(cond, reference_latents=[odd])
    )
    assert out_odd.shape == base.shape


def test_usdu_on_flux_with_reference_latents(bundle):
    """The USDU tile path windows reference latents per tile
    (reference crop_reference_latents) and the model consumes them."""
    from comfyui_distributed_tpu.ops import upscale as up
    from comfyui_distributed_tpu.ops.conditioning import Conditioning

    rng = np.random.default_rng(13)
    img = jnp.asarray(rng.random((1, 64, 64, 3)), dtype=jnp.float32)
    ref = jnp.asarray(rng.random((1, 16, 16, 16)), dtype=jnp.float32)
    pos = Conditioning(
        context=pl.encode_text(bundle, ["p"]), reference_latents=[ref]
    )
    neg = pl.encode_text(bundle, [""])
    out = up.run_upscale(
        bundle, img, pos, neg, mesh=None, upscale_by=2.0, tile=64,
        padding=16, steps=2, denoise=0.4, seed=3,
    )
    assert out.shape == (1, 128, 128, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_usdu_mesh_matches_single_on_flux(bundle):
    """Tile sharding over 8 chips is numerically equivalent to the
    local scan for the flow family too — folded per-tile keys and the
    interpolation noising are participant-independent."""
    from comfyui_distributed_tpu.ops import upscale as up
    from comfyui_distributed_tpu.parallel import build_mesh

    rng = np.random.default_rng(9)
    img = jnp.asarray(rng.random((1, 64, 64, 3)), dtype=jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    kwargs = dict(upscale_by=2.0, tile=64, padding=16, steps=2,
                  denoise=0.4, seed=7, tile_batch=1)
    single = up.run_upscale(bundle, img, pos, neg, mesh=None, **kwargs)
    mesh = build_mesh({"data": 8})
    sharded = up.run_upscale(bundle, img, pos, neg, mesh=mesh, **kwargs)
    np.testing.assert_allclose(
        np.asarray(single), np.asarray(sharded), atol=2e-2, rtol=0
    )


def test_flux_schedule_roundtrip_exact(bundle):
    """Every MMDiT template leaf is covered by the flux key schedule,
    bit-exactly, through the synthesize → convert round trip."""
    cfg = get_config("tiny-flux")
    flat = flatten_params(jax.device_get(bundle.params["unet"]))
    schedule = sdc.flux_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, schedule)
    converted, missing = sdc.convert_state_dict(state_dict, schedule)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)


def test_load_flux_weights_transformer_only(bundle):
    """A bare flux1-*.safetensors (transformer only) maps the unet and
    leaves VAE/text encoders at init without complaint — published
    Flux weights ship as separate files."""
    cfg = get_config("tiny-flux")
    flat = flatten_params(jax.device_get(bundle.params["unet"]))
    state_dict = sdc.synthesize_state_dict(flat, sdc.flux_schedule(cfg))
    templates = {
        "unet": bundle.params["unet"],
        "vae": bundle.params["vae"],
        "te": bundle.params["te"],
        "te2": bundle.params["te2"],
    }
    out, problems = sdc.load_sd_weights(
        state_dict, cfg, get_config("tiny-vae-flux"),
        get_config("tiny-t5-shared"), templates,
        te2_cfg=get_config("tiny-te"), family="mmdit",
    )
    assert problems == []
    got = flatten_params(out["unet"])
    for key, want in flat.items():
        np.testing.assert_array_equal(got[key], np.asarray(want), err_msg=key)
    # untouched parts stay at init
    np.testing.assert_array_equal(
        flatten_params(out["vae"])[
            sorted(flatten_params(out["vae"]))[0]
        ],
        flatten_params(jax.device_get(bundle.params["vae"]))[
            sorted(flatten_params(jax.device_get(bundle.params["vae"])))[0]
        ],
    )


def test_t5_shared_rel_bias_tree():
    """tiny-t5-shared (Flux T5 v1.1 layout): one top-level rel_bias,
    none inside blocks — and the schedule maps it."""
    from comfyui_distributed_tpu.models.registry import create_model

    cfg = get_config("tiny-t5-shared")
    te = create_model("tiny-t5-shared")
    params = te.init(
        jax.random.key(0), jnp.zeros((1, cfg.max_length), jnp.int32)
    )
    flat = flatten_params(jax.device_get(params))
    assert "params/rel_bias/embedding" in flat
    assert not any("block_0/rel_bias" in k for k in flat)
    schedule = sdc.t5_encoder_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, schedule)
    converted, missing = sdc.convert_state_dict(state_dict, schedule)
    assert not missing and set(converted) == set(flat)
