"""WAN DiT checkpoint key mapping: schedule round-trips + real-key
structure pins (same strategy as test_sd_checkpoint.py — synthesize a
torch-layout state dict from a random-init flax tree via the inverse
schedule, convert back, and require exact coverage)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params

pytestmark = pytest.mark.slow


def _dit_template(name: str):
    model = create_model(name)
    cfg = get_config(name)
    params = model.init(
        jax.random.key(0),
        jnp.zeros((1, 2, 8, 8, cfg.in_channels)),
        jnp.zeros((1,)),
        jnp.zeros((1, 8, cfg.context_dim)),
    )
    return cfg, params


def test_wan_schedule_roundtrip_exact():
    cfg, params = _dit_template("tiny-dit")
    flat = flatten_params(jax.device_get(params))
    entries = sdc.wan_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, entries)
    converted, missing = sdc.convert_state_dict(state_dict, entries)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)


# Genuine key names from the public WAN 2.1 t2v DiT state dict layout.
WAN_KNOWN_KEYS = [
    "patch_embedding.weight",
    "patch_embedding.bias",
    "text_embedding.0.weight",
    "text_embedding.2.bias",
    "time_embedding.0.weight",
    "time_embedding.2.weight",
    "time_projection.1.weight",
    "blocks.0.self_attn.q.weight",
    "blocks.0.self_attn.q.bias",
    "blocks.0.self_attn.norm_q.weight",
    "blocks.0.self_attn.norm_k.weight",
    "blocks.0.self_attn.o.weight",
    "blocks.0.cross_attn.k.weight",
    "blocks.0.cross_attn.norm_q.weight",
    "blocks.0.norm3.weight",
    "blocks.0.norm3.bias",
    "blocks.0.ffn.0.weight",
    "blocks.0.ffn.2.bias",
    "blocks.0.modulation",
    "blocks.29.ffn.2.weight",
    "head.head.weight",
    "head.head.bias",
    "head.modulation",
]


def test_wan13b_schedule_covers_real_key_names():
    cfg = get_config("wan-1.3b")
    keys = {k for k, _f, _h in sdc._expand(sdc.wan_schedule(cfg))}
    missing = [k for k in WAN_KNOWN_KEYS if k not in keys]
    assert not missing, missing
    # 27 tensors per block (8 attn linears w+b, 2 rms scales, per attn
    # pair = 20; norm3 w+b; ffn 2x(w+b); modulation) x 30 blocks + 15
    # top-level (patch 2, text 4, time_embed 4, time_proj 2, head 3)
    assert len(keys) == 27 * 30 + 15, len(keys)


def test_wan_schedule_shapes_match_published_dims():
    """The wan-1.3b synthesized checkpoint carries WAN 2.1-1.3B's
    published tensor shapes (dim 1536, ffn 8960, text 4096, 6-way
    modulation) — pinning the config to the real architecture."""
    cfg = get_config("wan-1.3b")
    shapes = jax.eval_shape(
        lambda k: create_model("wan-1.3b").init(
            k,
            jnp.zeros((1, 2, 8, 8, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 8, cfg.context_dim)),
        ),
        jax.random.key(0),
    )
    flat = {}

    def walk(node, path):
        if isinstance(node, dict):
            for key, value in node.items():
                walk(value, f"{path}/{key}" if path else str(key))
        else:
            flat[path] = node

    walk(shapes, "")
    assert flat["params/block_0/ffn_0/kernel"].shape == (1536, 8960)
    assert flat["params/block_0/modulation"].shape == (1, 6, 1536)
    assert flat["params/text_embed_0/kernel"].shape == (4096, 1536)
    assert flat["params/time_proj/kernel"].shape == (1536, 9216)
    assert flat["params/patch_embed/kernel"].shape == (1 * 2 * 2 * 16, 1536)
    assert flat["params/head_modulation"].shape == (1, 2, 1536)
    # schedule covers the full tree exactly
    flax_paths = {
        f"params/{fx}" for _sd, fx, _how in sdc._expand(sdc.wan_schedule(cfg))
    }
    assert set(flat) == flax_paths, (
        sorted(set(flat) - flax_paths)[:8],
        sorted(flax_paths - set(flat))[:8],
    )


def test_load_wan_weights_roundtrip_and_prefix():
    cfg, params = _dit_template("tiny-dit")
    flat = flatten_params(jax.device_get(params))
    state_dict = sdc.synthesize_state_dict(flat, sdc.wan_schedule(cfg))

    out, problems = sdc.load_wan_weights(state_dict, cfg, params)
    assert problems == []
    got = flatten_params(out)
    for key in flat:
        np.testing.assert_array_equal(got[key], flat[key], err_msg=key)

    # ComfyUI-repacked prefix is auto-detected
    prefixed = {f"model.diffusion_model.{k}": v for k, v in state_dict.items()}
    out2, problems2 = sdc.load_wan_weights(prefixed, cfg, params)
    assert problems2 == []
    got2 = flatten_params(out2)
    np.testing.assert_array_equal(
        got2["params/block_0/self_attn_q/kernel"],
        flat["params/block_0/self_attn_q/kernel"],
    )


def test_load_wan_weights_strict_on_missing():
    cfg, params = _dit_template("tiny-dit")
    with pytest.raises(ValueError, match="WAN checkpoint mapping failed"):
        sdc.load_wan_weights({}, cfg, params)


def test_conv3d_transform_matches_torch_conv_semantics():
    """The patch-embedding mapping is numerics-exact: a torch-layout
    Conv3d kernel applied as stride=patch conv equals the DiT's
    patchify-then-dense with the transformed kernel."""
    rng = np.random.default_rng(3)
    pf, ph, pw, cin, out = 1, 2, 2, 4, 6
    w = rng.normal(size=(out, cin, pf, ph, pw)).astype(np.float32)
    x = rng.normal(size=(pf, ph, pw, cin)).astype(np.float32)  # one patch

    # torch conv correlate: sum over (c, i, j, k) of w[o,c,i,j,k]*x[i,j,k,c]
    want = np.einsum("ocijk,ijkc->o", w, x)
    kernel = sdc._transform(w, f"conv3d:{pf}:{ph}:{pw}:{cin}")
    got = x.reshape(-1) @ kernel  # DiT flatten order (pf, ph, pw, c)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # inverse round-trips
    back = sdc._inverse_transform(kernel, f"conv3d:{pf}:{ph}:{pw}:{cin}")
    np.testing.assert_array_equal(back, w)


def test_video_pipeline_reads_wan_checkpoint(tmp_path, monkeypatch):
    """End-to-end: a synthetic WAN-layout safetensors file resolves via
    CDT_CHECKPOINT_DIR and its weights land in the video bundle."""
    from safetensors.numpy import save_file

    from comfyui_distributed_tpu.models import video_pipeline as vp

    cfg, params = _dit_template("tiny-dit")
    rng = np.random.default_rng(11)
    synth = sdc.synthesize_state_dict(
        flatten_params(jax.device_get(params)), sdc.wan_schedule(cfg)
    )
    state_dict = {
        k: (v + rng.normal(0, 0.01, v.shape)).astype(np.float32)
        for k, v in synth.items()
    }
    save_file(state_dict, str(tmp_path / "tiny-dit.safetensors"))
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))

    bundle = vp.load_video_pipeline("tiny-dit", seed=0)
    got = flatten_params(jax.device_get(bundle.params["unet"]))
    key = "params/block_0/self_attn_q/kernel"
    expect = sdc._transform(state_dict["blocks.0.self_attn.q.weight"], "linear")
    np.testing.assert_allclose(got[key], expect, rtol=1e-6)
    init = flatten_params(jax.device_get(params))
    assert np.abs(got[key] - init[key]).max() > 0  # not random init
