"""Causal 3D video VAE: shape contracts, causality, schedule
round-trip + real-key pins, pipeline integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params

pytestmark = pytest.mark.slow


def _tiny():
    model = create_model("tiny-video-vae-3d")
    cfg = get_config("tiny-video-vae-3d")
    x = jnp.zeros((1, cfg.temporal_downscale + 1, 16, 16, 3))
    params = model.init(jax.random.key(0), x)
    return model, cfg, params


def test_shape_contract_round_trip():
    """encode: F = tn+1 → (F-1)/t + 1 latent frames, H/downscale
    spatial; decode inverts exactly."""
    model, cfg, params = _tiny()
    t = cfg.temporal_downscale
    for n in (1, 3):
        f = t * n + 1
        x = jnp.asarray(
            np.random.default_rng(n).uniform(size=(1, f, 16, 16, 3)),
            jnp.float32,
        )
        z = model.apply(params, x, method="encode")
        assert z.shape == (1, n + 1, 16 // cfg.downscale, 16 // cfg.downscale,
                           cfg.z_dim)
        y = model.apply(params, z, method="decode")
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()


def test_frame_contract_rejected():
    model, cfg, params = _tiny()
    bad = jnp.zeros((1, cfg.temporal_downscale, 16, 16, 3))
    with pytest.raises(ValueError, match="causal contract"):
        model.apply(params, bad, method="encode")


def test_temporal_causality():
    """Changing a LATER frame must not change EARLIER latent frames
    (the whole point of causal convolutions)."""
    model, cfg, params = _tiny()
    t = cfg.temporal_downscale
    f = 2 * t + 1
    rng = np.random.default_rng(3)
    x = np.asarray(rng.uniform(size=(1, f, 16, 16, 3)), np.float32)
    x2 = x.copy()
    x2[:, -1] += 0.5  # perturb only the last frame
    z1 = np.asarray(model.apply(params, jnp.asarray(x), method="encode"))
    z2 = np.asarray(model.apply(params, jnp.asarray(x2), method="encode"))
    # the first latent frame depends only on pixel frame 0
    np.testing.assert_allclose(z1[:, 0], z2[:, 0], atol=1e-5)
    assert np.abs(z1[:, -1] - z2[:, -1]).max() > 1e-4  # it did change


def test_downsample_frame0_bypasses_time_conv():
    """Wan2.1 Resample downsample3d streaming semantics: the first
    chunk is only *cached*, never convolved — so frame 0 of the
    temporal stage is the spatially-downsampled frame 0 verbatim, and
    later frames come from windows [x0,x1,x2], [x2,x3,x4], ..."""
    from comfyui_distributed_tpu.models.video_vae import _Downsample

    mod = _Downsample(dim=4, temporal=True)
    x = jnp.asarray(
        np.random.default_rng(0).uniform(size=(1, 5, 8, 8, 4)), jnp.float32
    )
    params = mod.init(jax.random.key(0), x)
    out = np.asarray(mod.apply(params, x))
    assert out.shape == (1, 3, 4, 4, 4)

    # Zero the temporal conv: convolved frames collapse to zero while
    # the cache-bypass frame 0 keeps the spatial conv output.
    zeroed = jax.tree_util.tree_map(jnp.zeros_like, params)
    zeroed["params"]["resample_1"] = params["params"]["resample_1"]
    out_z = np.asarray(mod.apply(zeroed, x))
    np.testing.assert_allclose(out_z[:, 0], out[:, 0], atol=1e-6)
    assert np.abs(out_z[:, 0]).max() > 1e-4
    np.testing.assert_allclose(out_z[:, 1:], 0.0, atol=1e-7)


def test_downsample_spatial_conv_runs_before_time_conv():
    """downsample3d applies the stride-2 spatial conv first; the
    temporal conv then sees spatially-reduced frames, so out[1]
    depends on pixel frames 0-2 and out[2] on frames 2-4 only."""
    from comfyui_distributed_tpu.models.video_vae import _Downsample

    mod = _Downsample(dim=4, temporal=True)
    rng = np.random.default_rng(1)
    x = np.asarray(rng.uniform(size=(1, 5, 8, 8, 4)), np.float32)
    params = mod.init(jax.random.key(1), jnp.asarray(x))
    base = np.asarray(mod.apply(params, jnp.asarray(x)))
    x2 = x.copy()
    x2[:, 1] += 0.25  # frame 1 is only in window [x0,x1,x2]
    out = np.asarray(mod.apply(params, jnp.asarray(x2)))
    np.testing.assert_allclose(out[:, 0], base[:, 0], atol=1e-6)
    assert np.abs(out[:, 1] - base[:, 1]).max() > 1e-4
    np.testing.assert_allclose(out[:, 2], base[:, 2], atol=1e-6)


def test_upsample_rep_boundary_z0_undoubled_and_excluded():
    """Wan2.1 Resample upsample3d 'Rep' semantics: z0 passes through
    un-doubled and never enters a time_conv window — perturbing z0
    changes ONLY output frame 0."""
    from comfyui_distributed_tpu.models.video_vae import _Upsample

    mod = _Upsample(dim=4, temporal=True)
    rng = np.random.default_rng(2)
    z = np.asarray(rng.uniform(size=(1, 3, 4, 4, 4)), np.float32)
    params = mod.init(jax.random.key(2), jnp.asarray(z))
    base = np.asarray(mod.apply(params, jnp.asarray(z)))
    assert base.shape == (1, 5, 8, 8, 2)  # 1 + 2*(L-1) frames

    z2 = z.copy()
    z2[:, 0] += 0.5
    out = np.asarray(mod.apply(params, jnp.asarray(z2)))
    assert np.abs(out[:, 0] - base[:, 0]).max() > 1e-4
    np.testing.assert_allclose(out[:, 1:], base[:, 1:], atol=1e-6)


def test_upsample_z1_windows_match_zero_padded_causal_conv():
    """Frames 1.. come from causal windows over [0, 0, z1, z2, ...]:
    zeroing the time_conv collapses every doubled frame to the
    (spatially upsampled) bias while frame 0 keeps z0's content."""
    from comfyui_distributed_tpu.models.video_vae import _Upsample

    mod = _Upsample(dim=4, temporal=True)
    z = jnp.asarray(
        np.random.default_rng(3).uniform(size=(1, 3, 4, 4, 4)), jnp.float32
    )
    params = mod.init(jax.random.key(3), z)
    zeroed = jax.tree_util.tree_map(jnp.zeros_like, params)
    zeroed["params"]["resample_1"] = params["params"]["resample_1"]
    out = np.asarray(mod.apply(zeroed, z))
    # all doubled frames identical (pure bias through the spatial conv)
    for i in range(2, 5):
        np.testing.assert_allclose(out[:, i], out[:, 1], atol=1e-6)
    assert np.abs(out[:, 0] - out[:, 1]).max() > 1e-4  # z0 content survives


def test_wan_vae_schedule_roundtrip_exact():
    model, cfg, params = _tiny()
    flat = flatten_params(jax.device_get(params))
    entries = sdc.wan_vae_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, entries)
    converted, missing = sdc.convert_state_dict(state_dict, entries)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:8],
        sorted(set(converted) - set(flat))[:8],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)

    out, problems = sdc.load_wan_vae_weights(state_dict, cfg, params)
    assert problems == []
    with pytest.raises(ValueError, match="WAN VAE checkpoint mapping failed"):
        sdc.load_wan_vae_weights({}, cfg, params)


# Genuine key names from the official Wan2.1 VAE state dict layout
# (flattened Sequential indices; bare .gamma RMS params).
WAN_VAE_KNOWN_KEYS = [
    "encoder.conv1.weight",
    "encoder.downsamples.0.residual.0.gamma",
    "encoder.downsamples.0.residual.2.weight",
    "encoder.downsamples.0.residual.6.bias",
    "encoder.downsamples.3.residual.0.gamma",  # level-1 first resblock
    "encoder.middle.1.norm.gamma",
    "encoder.middle.1.to_qkv.weight",
    "encoder.head.0.gamma",
    "encoder.head.2.weight",
    "conv1.weight",
    "conv2.weight",
    "decoder.conv1.weight",
    "decoder.middle.0.residual.2.weight",
    "decoder.upsamples.0.residual.0.gamma",
    "decoder.head.2.bias",
]


def test_wan_vae_full_config_covers_real_key_names():
    cfg = get_config("wan-vae")
    keys = {k for k, _f, _h in sdc._expand(sdc.wan_vae_schedule(cfg))}
    missing = [k for k in WAN_VAE_KNOWN_KEYS if k not in keys]
    assert not missing, missing
    # full config: downsample stages at indices 2, 5, 8 with time_conv
    # on the temporal levels only (WAN: levels 1 and 2)
    assert "encoder.downsamples.2.resample.1.weight" in keys
    assert "encoder.downsamples.2.time_conv.weight" not in keys
    assert "encoder.downsamples.5.time_conv.weight" in keys
    assert "encoder.downsamples.8.time_conv.weight" in keys
    # decoder: 15 modules (3 res + resample per level, 3 res at last)
    assert "decoder.upsamples.14.residual.2.weight" in keys
    assert "decoder.upsamples.15.residual.2.weight" not in keys


def test_pipeline_with_3d_vae():
    """t2v through the causal VAE: 4n+1 pixel frames sampled in
    compressed latent time."""
    from comfyui_distributed_tpu.models.video_pipeline import (
        load_video_pipeline,
        t2v,
    )

    bundle = load_video_pipeline("tiny-dit", vae_name="tiny-video-vae-3d")
    assert bundle.temporal_scale == 2
    out = t2v(bundle, "drifting clouds", frames=5, height=32, width=32, steps=2)
    assert out.shape[:2] == (1, 5)
    assert np.isfinite(np.asarray(out, np.float32)).all()
    with pytest.raises(ValueError, match="causal contract"):
        t2v(bundle, "x", frames=4, height=32, width=32, steps=2)
