"""GGUF reader: format round-trips, dequant formula pins, and
integration with the checkpoint loader."""

import numpy as np
import pytest

from comfyui_distributed_tpu.models import gguf


def test_f32_f16_roundtrip(tmp_path):
    path = str(tmp_path / "m.gguf")
    rng = np.random.default_rng(0)
    a = rng.normal(size=(3, 5)).astype(np.float32)
    b = rng.normal(size=(2, 4, 6)).astype(np.float32)
    gguf.write_gguf(
        path, {"a": (a, gguf.GGML_F32), "b": (b, gguf.GGML_F16)},
        metadata={"general.architecture": "test"},
    )
    out = gguf.read_gguf(path)
    np.testing.assert_array_equal(out["a"], a)
    np.testing.assert_allclose(out["b"], b.astype(np.float16), atol=1e-3)
    assert out["a"].shape == a.shape and out["b"].shape == b.shape


@pytest.mark.parametrize(
    "gtype,atol_scale",
    [(gguf.GGML_Q8_0, 1 / 127), (gguf.GGML_Q4_0, 1 / 7), (gguf.GGML_Q5_0, 1 / 15)],
)
def test_quant_roundtrip_within_tolerance(tmp_path, gtype, atol_scale):
    path = str(tmp_path / "q.gguf")
    rng = np.random.default_rng(1)
    x = rng.normal(size=(4, 64)).astype(np.float32)
    gguf.write_gguf(path, {"x": (x, gtype)})
    out = gguf.read_gguf(path)["x"]
    assert out.shape == x.shape
    # block-wise quantization error bounded by the step size
    max_abs = np.abs(x).max()
    assert np.abs(out - x).max() < max_abs * atol_scale * 1.2


def test_q8_0_dequant_formula_exact():
    """Hand-built Q8_0 block: dequant must be exactly d * q."""
    import struct

    d = np.float16(0.5)
    q = np.arange(-16, 16, dtype=np.int8)
    raw = np.frombuffer(d.tobytes() + q.tobytes(), dtype=np.uint8)
    out = gguf._dequant(raw, gguf.GGML_Q8_0, 32)
    np.testing.assert_allclose(out, 0.5 * q.astype(np.float32))


def test_q4_0_dequant_formula_exact():
    d = np.float16(2.0)
    # nibbles: lower nibble = elements 0..15, upper = 16..31
    lo = np.arange(16, dtype=np.uint8)
    hi = np.full(16, 15, dtype=np.uint8)
    packed = (lo | (hi << 4)).astype(np.uint8)
    raw = np.frombuffer(d.tobytes() + packed.tobytes(), dtype=np.uint8)
    out = gguf._dequant(raw, gguf.GGML_Q4_0, 32)
    expect = np.concatenate([
        2.0 * (lo.astype(np.float32) - 8.0),
        2.0 * (hi.astype(np.float32) - 8.0),
    ])
    np.testing.assert_allclose(out, expect)


def test_non_block_multiple_length(tmp_path):
    """Tensor sizes that aren't multiples of 32 pad at write and trim
    at read."""
    path = str(tmp_path / "odd.gguf")
    x = np.linspace(-1, 1, 37, dtype=np.float32).reshape(37)
    gguf.write_gguf(path, {"x": (x, gguf.GGML_Q8_0)})
    out = gguf.read_gguf(path)["x"]
    assert out.shape == (37,)
    assert np.abs(out - x).max() < 0.02


def test_unsupported_type_raises(tmp_path):
    path = str(tmp_path / "bad.gguf")
    x = np.zeros(32, np.float32)
    gguf.write_gguf(path, {"x": (x, gguf.GGML_F32)})
    # corrupt the tensor-type field to a K-quant id (12): locate the
    # unique (n_dims=1, dim=32, type=F32) info record and patch type
    import struct

    data = bytearray(open(path, "rb").read())
    marker = struct.pack("<IQ", 1, 32) + struct.pack("<I", gguf.GGML_F32)
    pos = bytes(data).find(marker)
    assert pos != -1
    data[pos + 12 : pos + 16] = struct.pack("<I", 12)  # Q3_K
    open(path, "wb").write(bytes(data))
    with pytest.raises(ValueError, match="unsupported ggml type 12"):
        gguf.read_gguf(path)


def test_read_checkpoint_routes_gguf(tmp_path):
    from comfyui_distributed_tpu.models import sd_checkpoint as sdc

    path = str(tmp_path / "model.gguf")
    x = np.ones((8, 4), np.float32)
    gguf.write_gguf(
        path, {"model.diffusion_model.out.2.weight": (x, gguf.GGML_F32)}
    )
    out = sdc.read_checkpoint(path)
    np.testing.assert_array_equal(
        out["model.diffusion_model.out.2.weight"], x
    )


def test_load_pipeline_from_quantized_gguf(tmp_path, monkeypatch):
    """End-to-end: a full tiny-unet SD checkpoint quantized to Q8_0 in
    a GGUF container loads through load_pipeline with weights close to
    the originals."""
    import jax

    from comfyui_distributed_tpu.models import pipeline as pl
    from comfyui_distributed_tpu.models import sd_checkpoint as sdc
    from comfyui_distributed_tpu.models import create_model, get_config
    from comfyui_distributed_tpu.models.io import flatten_params
    import jax.numpy as jnp

    bundle0 = pl.load_pipeline("tiny-unet", seed=3)
    state_dict = {}
    for part, schedule, cfg_name in (
        ("unet", sdc.unet_schedule, "tiny-unet"),
        ("vae", sdc.vae_schedule, "tiny-vae"),
        ("te", sdc.text_encoder_schedule, "tiny-te"),
    ):
        state_dict.update(sdc.synthesize_state_dict(
            flatten_params(jax.device_get(bundle0.params[part])),
            schedule(get_config(cfg_name)),
        ))
    path = str(tmp_path / "tiny-unet.gguf")
    gguf.write_gguf(
        path,
        {k: (np.asarray(v, np.float32),
             gguf.GGML_Q8_0 if np.asarray(v).ndim >= 2 and np.asarray(v).size % 32 == 0
             else gguf.GGML_F32)
         for k, v in state_dict.items()},
    )
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    got = flatten_params(jax.device_get(bundle.params["unet"]))
    want = flatten_params(jax.device_get(bundle0.params["unet"]))
    key = "params/input_conv/kernel"
    scale = np.abs(want[key]).max()
    assert np.abs(got[key] - want[key]).max() < scale * 0.02
