"""Weight IO: safetensors round-trip, strict mismatch detection, orbax
run-state save/restore of sharded params."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import create_model, get_config, io
from comfyui_distributed_tpu.parallel import build_mesh
from comfyui_distributed_tpu.parallel.sharding import shard_params


def _tiny_params():
    unet = create_model("tiny-unet")
    cfg = get_config("tiny-unet")
    return unet.init(
        jax.random.key(0), jnp.zeros((1, 16, 16, 4)), jnp.zeros((1,)),
        jnp.zeros((1, 8, cfg.context_dim)),
    )


def test_safetensors_roundtrip(tmp_path):
    params = _tiny_params()
    path = str(tmp_path / "ckpt.safetensors")
    io.save_params(params, path)
    loaded = io.load_params_into(params, path, strict=True)
    flat_a = io.flatten_params(jax.device_get(params))
    flat_b = io.flatten_params(loaded)
    assert set(flat_a) == set(flat_b)
    for key in flat_a:
        np.testing.assert_array_equal(flat_a[key], flat_b[key])


def test_strict_mismatch_raises(tmp_path):
    params = _tiny_params()
    path = str(tmp_path / "ckpt.safetensors")
    io.save_params(params, path)
    other = {"different": {"tree": np.zeros((3,), np.float32)}}
    with pytest.raises(ValueError):
        io.load_params_into(other, path, strict=True)
    # non-strict keeps the template
    merged = io.load_params_into(other, path, strict=False)
    np.testing.assert_array_equal(merged["different"]["tree"], np.zeros((3,)))


def test_orbax_run_state_sharded(tmp_path):
    mesh = build_mesh({"data": 2, "model": 4})
    params = shard_params({"w": np.arange(32, dtype=np.float32).reshape(8, 4)}, mesh)
    state = {"params": params, "step": jnp.asarray(7)}
    io.save_run_state(state, str(tmp_path / "run"), step=7)
    restored = io.load_run_state(state, str(tmp_path / "run"))
    assert int(restored["step"]) == 7
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]), np.asarray(params["w"])
    )
