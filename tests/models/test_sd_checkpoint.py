"""SD checkpoint key mapping: schedule round-trips + real-key-name
structure checks.

The round-trip tests synthesize a torch-layout SD state dict from a
random-init flax tree via the inverse schedule, convert it back, and
require exact coverage — proving every flax leaf has exactly one SD
key with the right transform. The name tests pin the schedule to the
genuine SD1.5/SDXL checkpoint key layout (curated from the public
checkpoint format) so the schedule can't drift into a shape that only
round-trips against itself.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params


def _template(name: str, kind: str):
    model = create_model(name)
    cfg = get_config(name)
    key = jax.random.key(0)
    if kind == "unet":
        params = model.init(
            key,
            jnp.zeros((1, 8, 8, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 8, cfg.context_dim)),
        )
    elif kind == "vae":
        params = model.init(key, jnp.zeros((1, 16, 16, 3)))
    else:
        params = model.init(key, jnp.zeros((1, cfg.max_length), jnp.int32))
    return cfg, params


@pytest.mark.parametrize(
    "name,kind,schedule",
    [
        ("tiny-unet", "unet", sdc.unet_schedule),
        ("tiny-unet-adm", "unet", sdc.unet_schedule),
        ("tiny-vae", "vae", sdc.vae_schedule),
        ("tiny-te", "te", sdc.text_encoder_schedule),
    ],
)
def test_schedule_roundtrip_exact(name, kind, schedule):
    cfg, params = _template(name, kind)
    flat = flatten_params(jax.device_get(params))
    state_dict = sdc.synthesize_state_dict(flat, schedule(cfg))
    converted, missing = sdc.convert_state_dict(state_dict, schedule(cfg))
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)


def test_load_sd_weights_full_pipeline():
    unet_cfg, unet_p = _template("tiny-unet", "unet")
    vae_cfg, vae_p = _template("tiny-vae", "vae")
    te_cfg, te_p = _template("tiny-te", "te")
    state_dict = {}
    state_dict.update(
        sdc.synthesize_state_dict(flatten_params(jax.device_get(unet_p)),
                                  sdc.unet_schedule(unet_cfg))
    )
    state_dict.update(
        sdc.synthesize_state_dict(flatten_params(jax.device_get(vae_p)),
                                  sdc.vae_schedule(vae_cfg))
    )
    state_dict.update(
        sdc.synthesize_state_dict(flatten_params(jax.device_get(te_p)),
                                  sdc.text_encoder_schedule(te_cfg))
    )
    out, problems = sdc.load_sd_weights(
        state_dict, unet_cfg, vae_cfg, te_cfg,
        {"unet": unet_p, "vae": vae_p, "te": te_p},
    )
    assert problems == []
    got = flatten_params(out["unet"])
    want = flatten_params(jax.device_get(unet_p))
    for key in want:
        np.testing.assert_array_equal(got[key], want[key], err_msg=key)


def test_load_sd_weights_strict_on_missing():
    unet_cfg, unet_p = _template("tiny-unet", "unet")
    vae_cfg, vae_p = _template("tiny-vae", "vae")
    te_cfg, te_p = _template("tiny-te", "te")
    with pytest.raises(ValueError, match="checkpoint mapping failed"):
        sdc.load_sd_weights(
            {}, unet_cfg, vae_cfg, te_cfg,
            {"unet": unet_p, "vae": vae_p, "te": te_p},
        )


# Genuine key names from the public SD1.5 checkpoint layout.
SD15_KNOWN_KEYS = [
    "model.diffusion_model.time_embed.0.weight",
    "model.diffusion_model.input_blocks.0.0.weight",
    "model.diffusion_model.input_blocks.1.0.in_layers.2.weight",
    "model.diffusion_model.input_blocks.1.1.transformer_blocks.0.attn1.to_q.weight",
    "model.diffusion_model.input_blocks.1.1.transformer_blocks.0.attn2.to_out.0.bias",
    "model.diffusion_model.input_blocks.1.1.transformer_blocks.0.ff.net.0.proj.weight",
    "model.diffusion_model.input_blocks.3.0.op.weight",
    "model.diffusion_model.input_blocks.4.0.skip_connection.weight",
    "model.diffusion_model.middle_block.1.proj_in.weight",
    "model.diffusion_model.output_blocks.2.1.conv.weight",
    "model.diffusion_model.output_blocks.5.2.conv.weight",
    "model.diffusion_model.output_blocks.11.1.transformer_blocks.0.norm3.weight",
    "model.diffusion_model.out.0.weight",
    "model.diffusion_model.out.2.bias",
    "first_stage_model.encoder.conv_in.weight",
    "first_stage_model.encoder.down.0.block.0.norm1.weight",
    "first_stage_model.encoder.down.0.downsample.conv.weight",
    "first_stage_model.encoder.down.1.block.0.nin_shortcut.weight",
    "first_stage_model.encoder.mid.attn_1.q.weight",
    "first_stage_model.quant_conv.weight",
    "first_stage_model.post_quant_conv.bias",
    "first_stage_model.decoder.up.1.upsample.conv.weight",
    "first_stage_model.decoder.up.3.block.2.conv2.weight",
    "cond_stage_model.transformer.text_model.embeddings.token_embedding.weight",
    "cond_stage_model.transformer.text_model.embeddings.position_embedding.weight",
    "cond_stage_model.transformer.text_model.encoder.layers.0.self_attn.q_proj.weight",
    "cond_stage_model.transformer.text_model.encoder.layers.11.mlp.fc2.bias",
    "cond_stage_model.transformer.text_model.final_layer_norm.weight",
]


def test_sd15_schedule_covers_real_key_names():
    """The sd15 config's expanded schedule must emit the real key
    names (no template init needed — pure key enumeration)."""
    keys = set()
    for schedule, cfg_name in (
        (sdc.unet_schedule, "sd15"),
        (sdc.vae_schedule, "vae-sd"),
        (sdc.text_encoder_schedule, "clip-l"),
    ):
        for sd_key, _fx, _how in sdc._expand(schedule(get_config(cfg_name))):
            keys.add(sd_key)
    missing = [k for k in SD15_KNOWN_KEYS if k not in keys]
    assert not missing, missing
    # SD1.5 totals: 686 UNet + 248 VAE + 196 text-encoder weight
    # tensors (checkpoints carry a 197th — the position_ids int buffer
    # — which is not a weight and is intentionally unmapped)
    unet_keys = [k for k in keys if k.startswith("model.diffusion_model")]
    vae_keys = [k for k in keys if k.startswith("first_stage_model")]
    te_keys = [k for k in keys if k.startswith("cond_stage_model")]
    assert len(unet_keys) == 686, len(unet_keys)
    assert len(vae_keys) == 248, len(vae_keys)
    assert len(te_keys) == 196, len(te_keys)


def test_sdxl_schedule_enumerates():
    """SDXL config expands without error and carries the label_emb +
    deep-mid keys that distinguish it."""
    keys = {
        k for k, _f, _h in sdc._expand(sdc.unet_schedule(get_config("sdxl")))
    }
    assert "model.diffusion_model.label_emb.0.0.weight" in keys
    assert (
        "model.diffusion_model.middle_block.1.transformer_blocks.9.attn1.to_q.weight"
        in keys
    )
    # SDXL level 0 has no attention
    assert not any("input_blocks.1.1" in k for k in keys)


def test_load_pipeline_reads_checkpoint(tmp_path, monkeypatch):
    """End-to-end: a synthetic SD-format safetensors checkpoint on disk
    is picked up via CDT_CHECKPOINT_DIR and its weights land in the
    pipeline bundle (distinguishable from random init)."""
    from safetensors.numpy import save_file

    from comfyui_distributed_tpu.models import pipeline as pl

    unet_cfg, unet_p = _template("tiny-unet", "unet")
    vae_cfg, vae_p = _template("tiny-vae", "vae")
    te_cfg, te_p = _template("tiny-te", "te")

    rng = np.random.default_rng(7)
    state_dict = {}
    for params, schedule, cfg in (
        (unet_p, sdc.unet_schedule, unet_cfg),
        (vae_p, sdc.vae_schedule, vae_cfg),
        (te_p, sdc.text_encoder_schedule, te_cfg),
    ):
        synth = sdc.synthesize_state_dict(
            flatten_params(jax.device_get(params)), schedule(cfg)
        )
        # perturb so loaded != random-init
        state_dict.update(
            {k: (v + rng.normal(0, 0.01, v.shape)).astype(np.float32)
             for k, v in synth.items()}
        )
    save_file(state_dict, str(tmp_path / "tiny-unet.safetensors"))
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(tmp_path))

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    got = flatten_params(jax.device_get(bundle.params["unet"]))
    key = "params/input_conv/kernel"
    expect = sdc._transform(
        state_dict["model.diffusion_model.input_blocks.0.0.weight"], "conv"
    )
    np.testing.assert_allclose(got[key], expect, rtol=1e-6)
    init = flatten_params(jax.device_get(unet_p))
    assert np.abs(got[key] - init[key]).max() > 0  # not random init


def test_find_checkpoint_file_requires_stem_match(tmp_path, monkeypatch):
    path = tmp_path / "sd15.safetensors"
    path.write_bytes(b"")
    monkeypatch.setenv("CDT_CHECKPOINT_DIR", str(path))
    assert sdc.find_checkpoint("sd15") == str(path)
    # a different model in the same process must NOT inherit the file
    assert sdc.find_checkpoint("tiny-unet") is None


def test_sd15_eval_shape_template_covered():
    """Via eval_shape (no weight materialization): every sd15 UNet flax
    leaf is covered by the schedule and no schedule path is dangling."""
    model = create_model("sd15")
    cfg = get_config("sd15")

    shapes = jax.eval_shape(
        lambda k: model.init(
            k,
            jnp.zeros((1, 8, 8, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 77, cfg.context_dim)),
        ),
        jax.random.key(0),
    )

    flat = {}

    def walk(node, path):
        if isinstance(node, dict):
            for key, value in node.items():
                walk(value, f"{path}/{key}" if path else str(key))
        else:
            flat[path] = node

    walk(shapes, "")
    flax_paths = {f"params/{fx}" for _sd, fx, _how in sdc._expand(sdc.unet_schedule(cfg))}
    missing = set(flat) - flax_paths
    dangling = flax_paths - set(flat)
    assert not missing, sorted(missing)[:8]
    assert not dangling, sorted(dangling)[:8]


def test_open_clip_schedule_roundtrip():
    """SDXL's bigG half: fused-qkv split + bare params round-trip."""
    cfg, params = _template("tiny-te-g", "te")
    flat = flatten_params(jax.device_get(params))
    entries = sdc.open_clip_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, entries)
    assert any(k.endswith(".attn.in_proj_weight") for k in state_dict)
    assert "conditioner.embedders.1.model.positional_embedding" in state_dict
    assert "conditioner.embedders.1.model.text_projection" in state_dict
    converted, missing = sdc.convert_state_dict(state_dict, entries)
    assert not missing
    assert set(converted) == set(flat)
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)


def test_sdxl_text_prefix_detected():
    """A checkpoint with conditioner.embedders.* keys maps the CLIP-L
    half from the SDXL prefix and the bigG half from open_clip."""
    te_cfg, te_p = _template("tiny-te-l", "te")
    te2_cfg, te2_p = _template("tiny-te-g", "te")
    unet_cfg, unet_p = _template("tiny-unet", "unet")
    vae_cfg, vae_p = _template("tiny-vae", "vae")

    state_dict = {}
    state_dict.update(sdc.synthesize_state_dict(
        flatten_params(jax.device_get(unet_p)), sdc.unet_schedule(unet_cfg)))
    state_dict.update(sdc.synthesize_state_dict(
        flatten_params(jax.device_get(vae_p)), sdc.vae_schedule(vae_cfg)))
    state_dict.update(sdc.synthesize_state_dict(
        flatten_params(jax.device_get(te_p)),
        sdc.text_encoder_schedule(
            te_cfg, prefix="conditioner.embedders.0.transformer.text_model"
        ),
    ))
    state_dict.update(sdc.synthesize_state_dict(
        flatten_params(jax.device_get(te2_p)), sdc.open_clip_schedule(te2_cfg)))

    out, problems = sdc.load_sd_weights(
        state_dict, unet_cfg, vae_cfg, te_cfg,
        {"unet": unet_p, "vae": vae_p, "te": te_p, "te2": te2_p},
        te2_cfg=te2_cfg,
    )
    assert problems == []
    got = flatten_params(out["te2"])
    want = flatten_params(jax.device_get(te2_p))
    for key in want:
        np.testing.assert_array_equal(got[key], want[key], err_msg=key)


def test_sd2_openclip_te_prefix_detected():
    """A checkpoint with cond_stage_model.model.* keys (SD2.x layout:
    OpenCLIP tower, bare positional embedding, fused in_proj) maps the
    text encoder through open_clip_schedule, not the HF-CLIP prefix."""
    te_cfg, te_p = _template("tiny-te-g", "te")  # OpenCLIP-shaped tiny TE
    unet_cfg, unet_p = _template("tiny-unet", "unet")
    vae_cfg, vae_p = _template("tiny-vae", "vae")

    state_dict = {}
    state_dict.update(sdc.synthesize_state_dict(
        flatten_params(jax.device_get(unet_p)), sdc.unet_schedule(unet_cfg)))
    state_dict.update(sdc.synthesize_state_dict(
        flatten_params(jax.device_get(vae_p)), sdc.vae_schedule(vae_cfg)))
    state_dict.update(sdc.synthesize_state_dict(
        flatten_params(jax.device_get(te_p)),
        sdc.open_clip_schedule(te_cfg, prefix="cond_stage_model.model"),
    ))

    out, problems = sdc.load_sd_weights(
        state_dict, unet_cfg, vae_cfg, te_cfg,
        {"unet": unet_p, "vae": vae_p, "te": te_p},
    )
    assert problems == []
    got = flatten_params(out["te"])
    want = flatten_params(jax.device_get(te_p))
    for key in want:
        np.testing.assert_array_equal(got[key], want[key], err_msg=key)
