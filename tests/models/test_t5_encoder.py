"""UMT5-class encoder: bucket-table semantics, forward invariances,
checkpoint schedule round-trip + real-key pins (same strategy as
test_sd_checkpoint.py / test_wan_checkpoint.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params
from comfyui_distributed_tpu.models.t5_encoder import (
    T5Tokenizer,
    relative_position_buckets,
)

pytestmark = pytest.mark.slow


def test_bucket_table_pins_t5_semantics():
    """Exact values from the T5 bidirectional bucket formula
    (num_buckets=32 → half=16, max_exact=8, log-spaced to 128)."""
    t = relative_position_buckets(256, 32, 128)
    assert t[0, 0] == 0                    # rel 0
    assert t[1, 0] == 1                    # key 1 before query → rp 1
    assert t[0, 1] == 17                   # key 1 after query → 16 + 1
    assert t[100, 0] == 15                 # rp 100 (behind): log bucket
    assert t[0, 100] == 31                 # rp 100 (ahead)
    assert t[0, 255] == 31                 # clamped at max
    assert t.max() == 31 and t.min() == 0


def test_forward_shapes_and_mask_invariance():
    """Pad tokens (id 0) must not influence non-pad positions: the same
    prompt with extra trailing padding produces identical hidden states
    at the shared positions."""
    model = create_model("tiny-t5")
    cfg = get_config("tiny-t5")
    short = np.zeros((1, 8), np.int32)
    short[0, :3] = [5, 7, 1]
    long = np.zeros((1, cfg.max_length), np.int32)
    long[0, :3] = [5, 7, 1]

    params = model.init(jax.random.key(0), jnp.asarray(long))
    h_long, pooled = model.apply(params, jnp.asarray(long))
    h_short, _ = model.apply(params, jnp.asarray(short))
    assert h_long.shape == (1, cfg.max_length, cfg.d_model)
    assert pooled.shape == (1, cfg.d_model)
    np.testing.assert_allclose(
        np.asarray(h_short[0, :3]), np.asarray(h_long[0, :3]),
        atol=2e-2,  # bf16 compute
    )


def test_t5_schedule_roundtrip_exact():
    model = create_model("tiny-t5")
    cfg = get_config("tiny-t5")
    params = model.init(
        jax.random.key(0), jnp.zeros((1, cfg.max_length), jnp.int32)
    )
    flat = flatten_params(jax.device_get(params))
    entries = sdc.t5_encoder_schedule(cfg)
    state_dict = sdc.synthesize_state_dict(flat, entries)
    converted, missing = sdc.convert_state_dict(state_dict, entries)
    assert not missing
    assert set(converted) == set(flat), (
        sorted(set(flat) - set(converted))[:5],
        sorted(set(converted) - set(flat))[:5],
    )
    for key in flat:
        np.testing.assert_array_equal(converted[key], flat[key], err_msg=key)

    out, problems = sdc.load_t5_weights(state_dict, cfg, params)
    assert problems == []
    got = flatten_params(out)
    np.testing.assert_array_equal(
        got["params/block_0/q/kernel"], flat["params/block_0/q/kernel"]
    )
    with pytest.raises(ValueError, match="T5 checkpoint mapping failed"):
        sdc.load_t5_weights({}, cfg, params)


# Genuine key names from the public UMT5 encoder (HF) layout.
UMT5_KNOWN_KEYS = [
    "shared.weight",
    "encoder.block.0.layer.0.SelfAttention.q.weight",
    "encoder.block.0.layer.0.SelfAttention.o.weight",
    "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight",
    "encoder.block.0.layer.0.layer_norm.weight",
    "encoder.block.0.layer.1.DenseReluDense.wi_0.weight",
    "encoder.block.0.layer.1.DenseReluDense.wi_1.weight",
    "encoder.block.0.layer.1.DenseReluDense.wo.weight",
    "encoder.block.23.layer.1.layer_norm.weight",
    "encoder.final_layer_norm.weight",
]


def test_umt5_schedule_covers_real_key_names():
    cfg = get_config("umt5-xxl")
    keys = {k for k, _f, _h in sdc._expand(sdc.t5_encoder_schedule(cfg))}
    missing = [k for k in UMT5_KNOWN_KEYS if k not in keys]
    assert not missing, missing
    # 10 tensors per block x 24 blocks + shared + final norm
    assert len(keys) == 10 * 24 + 2, len(keys)


def test_t5_tokenizer_fallback_deterministic():
    tok = T5Tokenizer(max_length=16)
    a = tok.encode("a photo of a cat")
    b = tok.encode("a photo of a cat")
    np.testing.assert_array_equal(a, b)
    assert a.shape == (16,)
    assert a.dtype == np.int32
    assert (a[a != 0] > 0).all()


def test_t5_tokenizer_fallback_folds_into_small_vocab(caplog):
    """The real t5-xxl embedding table (32128) is smaller than the
    CLIP-BPE fallback id space (49408); XLA gather would silently clamp
    out-of-range ids, so the tokenizer must fold them into range
    deterministically and warn loudly (ADVICE r4, medium)."""
    import logging

    vocab = get_config("t5-xxl").vocab_size
    tok = T5Tokenizer(max_length=16, vocab_size=vocab)
    text = "driving thru the canyon"  # "thru" → id ≥ 32128 in this vocab
    unfolded = T5Tokenizer(max_length=16).encode(text)
    assert (unfolded >= vocab).any(), "fixture must exercise overflow"
    with caplog.at_level(logging.WARNING, logger="cdt.t5_encoder"):
        folded = tok.encode(text)
    assert (folded < vocab).all()
    # pad mask unchanged: folded ids never land on pad(0)/eos(1)
    np.testing.assert_array_equal(folded == 0, unfolded == 0)
    assert folded[unfolded == 1].tolist() == unfolded[unfolded == 1].tolist()
    # in-range ids pass through untouched
    keep = (unfolded < vocab) & (unfolded != 0)
    np.testing.assert_array_equal(folded[keep], unfolded[keep])
    # deterministic across instances
    np.testing.assert_array_equal(
        folded, T5Tokenizer(max_length=16, vocab_size=vocab).encode(text)
    )
    assert any("folded into the valid range" in r.message for r in caplog.records)
    assert not tok.is_canonical


def test_t5_spm_vocab_larger_than_embedding_raises(tmp_path, monkeypatch):
    """A REAL sentencepiece vocab paired with a smaller embedding table
    is a misconfiguration (e.g. a umt5 asset on a t5-xxl encoder):
    folding real ids would corrupt real weights, so construction must
    fail loudly instead."""

    class StubSpm:
        vocab_size = 256384

        def __init__(self, vocab_file):
            pass

    import transformers

    monkeypatch.setattr(transformers, "T5TokenizerFast", StubSpm)
    spm = tmp_path / "umt5.model"
    spm.write_bytes(b"stub")
    with pytest.raises(ValueError, match="wrong vocab for this model"):
        T5Tokenizer(max_length=16, spm_path=str(spm), vocab_size=32128)
    # matching table accepted
    tok = T5Tokenizer(max_length=16, spm_path=str(spm), vocab_size=256384)
    assert tok.is_canonical


def test_t5_vocab_canonical_helper_cached(monkeypatch):
    from comfyui_distributed_tpu.models import t5_encoder as t5e

    monkeypatch.delenv("CDT_T5_SPM", raising=False)
    assert t5e.t5_vocab_canonical() is False
    assert "" in t5e._T5_CANONICAL_CACHE


def test_t5_tokenizer_large_vocab_never_folds():
    cfg = get_config("umt5-xxl")
    text = "driving thru the canyon"
    a = T5Tokenizer(max_length=16, vocab_size=cfg.vocab_size).encode(text)
    b = T5Tokenizer(max_length=16).encode(text)
    np.testing.assert_array_equal(a, b)


def test_video_pipeline_with_t5_encoder():
    from comfyui_distributed_tpu.models.video_pipeline import (
        encode_video_text,
        load_video_pipeline,
    )

    bundle = load_video_pipeline("tiny-dit", te_name="tiny-t5")
    ctx = encode_video_text(bundle, ["a red cube"])
    cfg = get_config("tiny-dit")
    assert ctx.shape[0] == 1 and ctx.shape[-1] == cfg.context_dim
    assert np.isfinite(np.asarray(ctx)).all()
