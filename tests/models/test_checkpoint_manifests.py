"""Pin checkpoint key schedules against committed key+shape manifests.

The manifests (tests/models/manifests/*.json, generated once by
scripts/gen_reference_manifests.py) enumerate the published
checkpoints' consumable state-dict layout from the TORCH side — the
original implementations' module construction — independently of the
flax trees and schedule code.  These tests derive each schedule's
(sd_key → torch shape) mapping via jax.eval_shape on the real-size
models and assert exact two-way agreement: a single renamed key or
wrong shape in a schedule fails here (the round-trip tests in
test_sd_checkpoint.py cannot catch that class of bug — an error there
reproduces identically in the synthesized checkpoint).

This replaces the loader guarantees the reference inherits for free
from ComfyUI's checkpoint code (reference upscale/tile_ops.py:168).
"""

import functools
import json
import os

import jax
import jax.numpy as jnp
import pytest

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.models import sd_checkpoint as sdc

pytestmark = pytest.mark.slow

MANIFEST_DIR = os.path.join(os.path.dirname(__file__), "manifests")


def _manifest(name: str) -> dict[str, tuple[int, ...]]:
    with open(os.path.join(MANIFEST_DIR, f"{name}.json")) as fh:
        return {k: tuple(v) for k, v in json.load(fh).items()}


@functools.lru_cache(maxsize=None)
def _flax_shapes(model_name: str) -> dict[str, tuple[int, ...]]:
    """Flat flax param path → shape for the real-size model, via
    eval_shape (no weight memory is allocated)."""
    cfg = get_config(model_name)
    key = jax.random.key(0)
    fam_inputs = {
        "unet": lambda: (
            jnp.zeros((1, 8, 8, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 77, cfg.context_dim)),
        ),
        "dit": lambda: (
            jnp.zeros((1, 1, 4, 4, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 16, cfg.context_dim)),
        )
        if not getattr(cfg, "i2v", False)
        else (
            jnp.zeros((1, 1, 4, 4, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 16, cfg.context_dim)),
            jnp.zeros((1, 257, cfg.img_dim)),
        ),
        "mmdit": lambda: (
            jnp.zeros((1, 8, 8, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 16, cfg.context_dim)),
            jnp.zeros((1, cfg.vec_dim)),
        ),
        "sd3": lambda: (
            jnp.zeros((1, 8, 8, cfg.in_channels)),
            jnp.zeros((1,)),
            jnp.zeros((1, 16, cfg.context_dim)),
            jnp.zeros((1, cfg.pooled_dim)),
        ),
        "vae": lambda: (jnp.zeros((1, 8, 8, cfg.in_channels)),),
        "text_encoder": lambda: (
            jnp.zeros((1, cfg.max_length), jnp.int32),
        ),
        "t5_encoder": lambda: (jnp.zeros((1, 8), jnp.int32),),
        "video_vae": lambda: (
            jnp.zeros((1, cfg.temporal_downscale + 1, 16, 16, 3)),
        ),
    }
    from comfyui_distributed_tpu.models.registry import model_family

    args = fam_inputs[model_family(model_name)]()
    tree = jax.eval_shape(lambda k: create_model(model_name).init(k, *args), key)
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for path, leaf in leaves:
        name = "/".join(
            p.key if hasattr(p, "key") else str(p) for p in path
        )
        out[name] = tuple(leaf.shape)
    return out


def _sd_shape(flax_shape: tuple[int, ...], how: str) -> tuple[int, ...]:
    """Forward-map a flax param shape to its torch state-dict shape —
    the shape-level mirror of sd_checkpoint._inverse_transform."""
    s = flax_shape
    if how == "conv":  # [kh,kw,I,O] → [O,I,kh,kw]
        return (s[3], s[2], s[0], s[1])
    if how == "linear":  # [I,O] → [O,I]
        return (s[1], s[0])
    if how == "proj":  # dense [I,O]; torch side may be 1x1 conv
        return (s[1], s[0])  # compared modulo trailing (1, 1)
    if how == "conv3d_k":  # [kt,kh,kw,I,O] → [O,I,kt,kh,kw]
        return (s[4], s[3], s[0], s[1], s[2])
    if how == "gamma3":
        return (s[0], 1, 1, 1)
    if how == "gamma2":
        return (s[0], 1, 1)
    if how.startswith("conv3d:"):
        pf, ph, pw, cin = (int(x) for x in how.split(":")[1:])
        return (s[-1], cin, pf, ph, pw)
    if how.startswith("conv2d:"):
        p, cin = (int(x) for x in how.split(":")[1:])
        return (s[-1], cin, p, p)
    if how.startswith("qkv"):  # fused in_proj: [I,O] → [3O,I] / [O] → [3O]
        if how.endswith("_w"):
            return (3 * s[1], s[0])
        return (3 * s[0],)
    return s  # id


def _schedule_sd_shapes(
    entries, model_name: str
) -> dict[str, tuple[int, ...]]:
    shapes = _flax_shapes(model_name)
    out: dict[str, tuple[int, ...]] = {}
    for sd_key, fx_path, how in sdc._expand(entries):
        flax_shape = shapes.get(f"params/{fx_path}")
        assert flax_shape is not None, f"schedule names missing flax param {fx_path}"
        out[sd_key] = _sd_shape(flax_shape, how)
    return out


def _assert_matches(
    derived: dict[str, tuple[int, ...]],
    manifest: dict[str, tuple[int, ...]],
    proj_conv_keys: bool,
) -> None:
    missing = sorted(set(manifest) - set(derived))
    extra = sorted(set(derived) - set(manifest))
    assert not missing, f"schedule misses {len(missing)} real keys: {missing[:8]}"
    assert not extra, f"schedule names {len(extra)} unreal keys: {extra[:8]}"
    bad = []
    for key, want in manifest.items():
        got = derived[key]
        if got != want:
            # 'proj' entries are dense on the flax side; SD1.x packs
            # them as 1x1 convs — identical modulo trailing (1, 1)
            if proj_conv_keys and want == got + (1, 1):
                continue
            bad.append((key, got, want))
    assert not bad, f"{len(bad)} shape mismatches: {bad[:8]}"


# --- SD1.5 -----------------------------------------------------------------

def test_sd15_unet_schedule_matches_manifest():
    manifest = _manifest("sd15")
    sub = {k: v for k, v in manifest.items() if k.startswith("model.diffusion_model.")}
    derived = _schedule_sd_shapes(
        sdc.unet_schedule(get_config("sd15")), "sd15"
    )
    _assert_matches(derived, sub, proj_conv_keys=True)


def test_sd15_vae_schedule_matches_manifest():
    manifest = _manifest("sd15")
    sub = {k: v for k, v in manifest.items() if k.startswith("first_stage_model.")}
    derived = _schedule_sd_shapes(sdc.vae_schedule(get_config("vae-sd")), "vae-sd")
    _assert_matches(derived, sub, proj_conv_keys=True)


def test_sd15_text_encoder_schedule_matches_manifest():
    manifest = _manifest("sd15")
    sub = {k: v for k, v in manifest.items() if k.startswith("cond_stage_model.")}
    derived = _schedule_sd_shapes(
        sdc.text_encoder_schedule(get_config("clip-l")), "clip-l"
    )
    _assert_matches(derived, sub, proj_conv_keys=False)


# --- SDXL ------------------------------------------------------------------

def test_sdxl_unet_schedule_matches_manifest():
    manifest = _manifest("sdxl")
    sub = {k: v for k, v in manifest.items() if k.startswith("model.diffusion_model.")}
    derived = _schedule_sd_shapes(sdc.unet_schedule(get_config("sdxl")), "sdxl")
    _assert_matches(derived, sub, proj_conv_keys=True)


def test_sdxl_clip_l_schedule_matches_manifest():
    manifest = _manifest("sdxl")
    prefix = "conditioner.embedders.0.transformer.text_model"
    sub = {k: v for k, v in manifest.items() if k.startswith(prefix)}
    derived = _schedule_sd_shapes(
        sdc.text_encoder_schedule(get_config("clip-l-sdxl"), prefix=prefix),
        "clip-l-sdxl",
    )
    _assert_matches(derived, sub, proj_conv_keys=False)


def test_sdxl_open_clip_schedule_matches_manifest():
    manifest = _manifest("sdxl")
    prefix = "conditioner.embedders.1.model"
    sub = {k: v for k, v in manifest.items() if k.startswith(prefix)}
    derived = _schedule_sd_shapes(
        sdc.open_clip_schedule(get_config("clip-g"), prefix=prefix), "clip-g"
    )
    _assert_matches(derived, sub, proj_conv_keys=False)


# --- SD2.1 -----------------------------------------------------------------

def test_sd21_unet_schedule_matches_manifest():
    manifest = _manifest("sd21")
    sub = {k: v for k, v in manifest.items() if k.startswith("model.diffusion_model.")}
    derived = _schedule_sd_shapes(sdc.unet_schedule(get_config("sd21")), "sd21")
    # SD2 uses linear transformer projections (like SDXL): no (1,1)
    # conv-tail tolerance
    _assert_matches(derived, sub, proj_conv_keys=False)


def test_sd21_open_clip_schedule_matches_manifest():
    manifest = _manifest("sd21")
    prefix = "cond_stage_model.model"
    sub = {k: v for k, v in manifest.items() if k.startswith(prefix)}
    derived = _schedule_sd_shapes(
        sdc.open_clip_schedule(get_config("clip-h"), prefix=prefix), "clip-h"
    )
    _assert_matches(derived, sub, proj_conv_keys=False)


# --- WAN -------------------------------------------------------------------

@pytest.mark.parametrize(
    "model_name,manifest_name",
    [
        ("wan-1.3b", "wan21_1_3b_dit"),
        ("wan-14b", "wan21_14b_dit"),
        ("wan-14b-i2v", "wan21_14b_i2v_dit"),
    ],
)
def test_wan_dit_schedule_matches_manifest(model_name, manifest_name):
    derived = _schedule_sd_shapes(
        sdc.wan_schedule(get_config(model_name)), model_name
    )
    _assert_matches(derived, _manifest(manifest_name), proj_conv_keys=False)


def test_wan_vae_schedule_matches_manifest():
    derived = _schedule_sd_shapes(
        sdc.wan_vae_schedule(get_config("wan-vae")), "wan-vae"
    )
    _assert_matches(derived, _manifest("wan21_vae"), proj_conv_keys=False)


# --- SD3 / SD3.5 -----------------------------------------------------------

@pytest.mark.parametrize(
    "model_name,manifest_name",
    [
        ("sd3-medium", "sd3_medium_dit"),
        ("sd35-large", "sd35_large_dit"),
        ("sd35-medium", "sd35_medium_dit"),
    ],
)
def test_sd3_schedule_matches_manifest(model_name, manifest_name):
    derived = _schedule_sd_shapes(
        sdc.sd3_schedule(get_config(model_name)), model_name
    )
    _assert_matches(derived, _manifest(manifest_name), proj_conv_keys=False)


def test_sd3_vae_schedule_matches_manifest():
    derived = _schedule_sd_shapes(
        sdc.vae_schedule(get_config("vae-sd3")), "vae-sd3"
    )
    _assert_matches(derived, _manifest("sd3_vae"), proj_conv_keys=True)


# --- Flux ------------------------------------------------------------------

@pytest.mark.parametrize(
    "model_name,manifest_name",
    [("flux-dev", "flux1_dev"), ("flux-schnell", "flux1_schnell")],
)
def test_flux_schedule_matches_manifest(model_name, manifest_name):
    derived = _schedule_sd_shapes(
        sdc.flux_schedule(get_config(model_name)), model_name
    )
    _assert_matches(derived, _manifest(manifest_name), proj_conv_keys=False)


def test_flux_ae_schedule_matches_manifest():
    derived = _schedule_sd_shapes(
        sdc.vae_schedule(get_config("vae-flux"), prefix=""), "vae-flux"
    )
    _assert_matches(derived, _manifest("flux_ae"), proj_conv_keys=True)


def test_t5_v11_schedule_matches_manifest():
    """Classic T5 v1.1 (Flux): rel bias on block 0 only — the schedule
    must not name per-layer bias keys the real file lacks."""
    manifest = _manifest("t5_xxl_encoder")
    assert (
        "encoder.block.23.layer.0.SelfAttention.relative_attention_bias.weight"
        not in manifest
    )
    derived = _schedule_sd_shapes(
        sdc.t5_encoder_schedule(get_config("t5-xxl")), "t5-xxl"
    )
    _assert_matches(derived, manifest, proj_conv_keys=False)


def test_umt5_schedule_matches_manifest():
    derived = _schedule_sd_shapes(
        sdc.t5_encoder_schedule(get_config("umt5-xxl")), "umt5-xxl"
    )
    _assert_matches(derived, _manifest("umt5_xxl_encoder"), proj_conv_keys=False)


# --- hand-pinned anchors ---------------------------------------------------

# Strategic keys with shapes as published by checkpoint inspectors —
# typed in by hand, NOT generated, so a shared bug between the
# generator and the schedules still fails here.
HAND_PINNED = {
    "sd15": {
        "model.diffusion_model.input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight": (320, 768),
        "model.diffusion_model.input_blocks.0.0.weight": (320, 4, 3, 3),
        "model.diffusion_model.middle_block.1.proj_in.weight": (1280, 1280, 1, 1),
        "model.diffusion_model.output_blocks.2.1.conv.weight": (1280, 1280, 3, 3),
        "model.diffusion_model.out.2.weight": (4, 320, 3, 3),
        "first_stage_model.encoder.mid.attn_1.q.weight": (512, 512, 1, 1),
        "first_stage_model.decoder.up.1.upsample.conv.weight": (256, 256, 3, 3),
        "first_stage_model.post_quant_conv.weight": (4, 4, 1, 1),
        "cond_stage_model.transformer.text_model.embeddings.token_embedding.weight": (49408, 768),
        "cond_stage_model.transformer.text_model.encoder.layers.11.mlp.fc1.weight": (3072, 768),
    },
    "sdxl": {
        "model.diffusion_model.label_emb.0.0.weight": (1280, 2816),
        "model.diffusion_model.input_blocks.4.1.proj_in.weight": (640, 640),
        "model.diffusion_model.input_blocks.7.1.transformer_blocks.9.attn2.to_k.weight": (1280, 2048),
        "model.diffusion_model.middle_block.1.transformer_blocks.0.ff.net.0.proj.weight": (10240, 1280),
        "model.diffusion_model.output_blocks.5.2.conv.weight": (640, 640, 3, 3),
        "conditioner.embedders.1.model.transformer.resblocks.31.attn.in_proj_weight": (3840, 1280),
        "conditioner.embedders.1.model.text_projection": (1280, 1280),
        "conditioner.embedders.1.model.positional_embedding": (77, 1280),
    },
    "sd21": {
        # v2-1_768-ema-pruned as listed by checkpoint inspectors:
        # linear transformer projections (2-D), OpenCLIP-H context 1024
        "model.diffusion_model.input_blocks.1.1.proj_in.weight": (320, 320),
        "model.diffusion_model.input_blocks.1.1.transformer_blocks.0.attn2.to_k.weight": (320, 1024),
        "model.diffusion_model.middle_block.1.proj_in.weight": (1280, 1280),
        "model.diffusion_model.out.2.weight": (4, 320, 3, 3),
        "cond_stage_model.model.token_embedding.weight": (49408, 1024),
        "cond_stage_model.model.positional_embedding": (77, 1024),
        "cond_stage_model.model.transformer.resblocks.23.attn.in_proj_weight": (3072, 1024),
        "cond_stage_model.model.text_projection": (1024, 1024),
        "cond_stage_model.model.ln_final.weight": (1024,),
    },
    "wan21_1_3b_dit": {
        "patch_embedding.weight": (1536, 16, 1, 2, 2),
        "blocks.29.ffn.0.weight": (8960, 1536),
        "blocks.0.modulation": (1, 6, 1536),
        "time_projection.1.weight": (9216, 1536),
        "head.head.weight": (64, 1536),
        "head.modulation": (1, 2, 1536),
    },
    "wan21_14b_i2v_dit": {
        # MLPProj: Linear(1280, 1280) then Linear(1280, 5120)
        "img_emb.proj.1.weight": (1280, 1280),
        "img_emb.proj.3.weight": (5120, 1280),
        "img_emb.proj.4.weight": (5120,),
        "blocks.0.cross_attn.k_img.weight": (5120, 5120),
        "patch_embedding.weight": (5120, 36, 1, 2, 2),
    },
    "wan21_vae": {
        "encoder.conv1.weight": (96, 3, 3, 3, 3),
        "encoder.downsamples.5.time_conv.weight": (192, 192, 3, 1, 1),
        "decoder.upsamples.3.time_conv.weight": (768, 384, 3, 1, 1),
        "decoder.upsamples.11.resample.1.weight": (96, 192, 3, 3),
        "conv2.weight": (16, 16, 1, 1, 1),
        "decoder.head.2.weight": (3, 96, 3, 3, 3),
    },
    "umt5_xxl_encoder": {
        "shared.weight": (256384, 4096),
        "encoder.block.23.layer.0.SelfAttention.relative_attention_bias.weight": (32, 64),
        "encoder.block.0.layer.1.DenseReluDense.wi_0.weight": (10240, 4096),
    },
    "flux1_dev": {
        # flux1-dev.safetensors as listed by checkpoint inspectors
        "img_in.weight": (3072, 64),
        "txt_in.weight": (3072, 4096),
        "time_in.in_layer.weight": (3072, 256),
        "guidance_in.in_layer.weight": (3072, 256),
        "vector_in.in_layer.weight": (3072, 768),
        "double_blocks.0.img_attn.qkv.weight": (9216, 3072),
        "double_blocks.18.txt_mlp.0.weight": (12288, 3072),
        "double_blocks.0.img_attn.norm.query_norm.scale": (128,),
        "single_blocks.37.linear1.weight": (21504, 3072),
        "single_blocks.0.linear2.weight": (3072, 15360),
        "final_layer.linear.weight": (64, 3072),
        "final_layer.adaLN_modulation.1.weight": (6144, 3072),
    },
    "flux_ae": {
        # ae.safetensors: bare keys, 16ch moments, no quant convs
        "encoder.conv_in.weight": (128, 3, 3, 3),
        "encoder.conv_out.weight": (32, 512, 3, 3),
        "decoder.conv_in.weight": (512, 16, 3, 3),
        "decoder.conv_out.weight": (3, 128, 3, 3),
    },
    "t5_xxl_encoder": {
        "shared.weight": (32128, 4096),
        "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight": (32, 64),
        "encoder.block.23.layer.1.DenseReluDense.wo.weight": (4096, 10240),
    },
    "sd35_large_dit": {
        # sd3.5_large.safetensors as listed by checkpoint inspectors
        "model.diffusion_model.x_embedder.proj.weight": (2432, 16, 2, 2),
        "model.diffusion_model.pos_embed": (1, 36864, 2432),
        "model.diffusion_model.context_embedder.weight": (2432, 4096),
        "model.diffusion_model.y_embedder.mlp.0.weight": (2432, 2048),
        "model.diffusion_model.t_embedder.mlp.0.weight": (2432, 256),
        "model.diffusion_model.joint_blocks.0.x_block.attn.qkv.weight": (7296, 2432),
        "model.diffusion_model.joint_blocks.0.x_block.attn.ln_q.weight": (64,),
        "model.diffusion_model.joint_blocks.37.context_block.adaLN_modulation.1.weight": (4864, 2432),
        "model.diffusion_model.final_layer.linear.weight": (64, 2432),
    },
    "sd3_medium_dit": {
        "model.diffusion_model.x_embedder.proj.weight": (1536, 16, 2, 2),
        "model.diffusion_model.pos_embed": (1, 36864, 1536),
        "model.diffusion_model.joint_blocks.0.x_block.attn.qkv.weight": (4608, 1536),
        "model.diffusion_model.final_layer.linear.weight": (64, 1536),
    },
    "sd35_medium_dit": {
        # sd3.5_medium.safetensors (MMDiT-X) as listed by checkpoint
        # inspectors: 384-wide learned pos table, attn2 branch with a
        # 9-way x adaLN in blocks 0-12, per-head qk RMS everywhere
        "model.diffusion_model.x_embedder.proj.weight": (1536, 16, 2, 2),
        "model.diffusion_model.pos_embed": (1, 147456, 1536),
        "model.diffusion_model.joint_blocks.0.x_block.attn2.qkv.weight": (4608, 1536),
        "model.diffusion_model.joint_blocks.0.x_block.attn2.ln_q.weight": (64,),
        "model.diffusion_model.joint_blocks.0.x_block.adaLN_modulation.1.weight": (13824, 1536),
        "model.diffusion_model.joint_blocks.13.x_block.adaLN_modulation.1.weight": (9216, 1536),
        "model.diffusion_model.final_layer.linear.weight": (64, 1536),
    },
}


@pytest.mark.parametrize("name", sorted(HAND_PINNED))
def test_manifests_contain_hand_pinned_published_shapes(name):
    manifest = _manifest(name)
    for key, shape in HAND_PINNED[name].items():
        assert key in manifest, f"manifest {name} lacks published key {key}"
        assert manifest[key] == shape, (key, manifest[key], shape)


def test_deliberate_rename_fails():
    """The guarantee the round-trip tests lack: a one-key rename in a
    schedule must fail the manifest comparison."""
    entries = sdc.wan_vae_schedule(get_config("wan-vae"))
    renamed = [
        ("encoder.conv1_RENAMED", fx, kind) if sd == "encoder.conv1" else (sd, fx, kind)
        for sd, fx, kind in entries
    ]
    derived = _schedule_sd_shapes(renamed, "wan-vae")
    with pytest.raises(AssertionError):
        _assert_matches(derived, _manifest("wan21_vae"), proj_conv_keys=False)
