"""CDT_PARAMS_DTYPE: bf16 weight storage for memory-constrained chips.

The reference inherits fp16/bf16 weight handling from ComfyUI's model
management (reference README "Lowvram" notes); here the env knob casts
floating-point params at bundle-build time in EVERY loader (pipeline,
video, VAE, ControlNet, upscaler) while integer leaves (embedding ids,
schedule tables) stay untouched. Unset ⇒ f32, the dtype the committed
goldens are pinned at.
"""

import jax.numpy as jnp
import pytest

from comfyui_distributed_tpu.models.pipeline import maybe_cast_params

pytestmark = pytest.mark.fast


def _tree():
    return {
        "w": jnp.ones((2, 2), jnp.float32),
        "ids": jnp.arange(3),
        "nested": {"b": jnp.zeros((4,), jnp.float32)},
    }


def test_unset_is_identity(monkeypatch):
    monkeypatch.delenv("CDT_PARAMS_DTYPE", raising=False)
    out = maybe_cast_params(_tree())
    assert out["w"].dtype == jnp.float32
    assert out["nested"]["b"].dtype == jnp.float32


def test_empty_string_is_identity(monkeypatch):
    monkeypatch.setenv("CDT_PARAMS_DTYPE", "")
    assert maybe_cast_params(_tree())["w"].dtype == jnp.float32


def test_bfloat16_casts_floats_only(monkeypatch):
    monkeypatch.setenv("CDT_PARAMS_DTYPE", "bfloat16")
    out = maybe_cast_params(_tree())
    assert out["w"].dtype == jnp.bfloat16
    assert out["nested"]["b"].dtype == jnp.bfloat16
    assert out["ids"].dtype == jnp.int32  # integer leaves untouched


def test_all_loaders_route_through_cast():
    """Every bundle-building loader must apply maybe_cast_params —
    an unrouted loader resurrects the 18.5G/15.75G SDXL HBM OOM this
    knob exists to fix (BENCH_NOTES.md round 5)."""
    import inspect

    from comfyui_distributed_tpu.models import (
        controlnet,
        pipeline,
        upscaler,
        video_pipeline,
    )

    for fn in (
        pipeline.load_pipeline,
        pipeline.load_vae,
        pipeline.load_unet,
        pipeline.load_clip,
        video_pipeline.load_video_pipeline,
        controlnet.load_controlnet,
        upscaler.load_upscale_model,
    ):
        src = inspect.getsource(fn)
        assert "maybe_cast_params" in src, fn.__qualname__
