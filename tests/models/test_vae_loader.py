"""Standalone VAE loading (VAELoader node): registry configs, both
checkpoint layouts (bare keys / first_stage_model.*), and drop-in
compatibility with every VAE-consuming node."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models import sd_checkpoint as sdc
from comfyui_distributed_tpu.models.io import flatten_params
from comfyui_distributed_tpu.models.registry import get_config

pytestmark = pytest.mark.slow


def test_load_vae_random_init_roundtrip():
    vb = pl.load_vae("tiny-vae")
    cfg = get_config("tiny-vae")
    assert vb.latent_channels == cfg.latent_channels
    assert vb.latent_scale == cfg.downscale
    img = jnp.full((1, 32, 32, 3), 0.5)
    z = vb.vae.apply(vb.params["vae"], img, method="encode")
    assert z.shape == (
        1, 32 // vb.latent_scale, 32 // vb.latent_scale, vb.latent_channels
    )
    out = vb.vae.apply(vb.params["vae"], z, method="decode")
    assert out.shape == img.shape


@pytest.mark.parametrize("prefix", ["", "first_stage_model"])
def test_load_vae_checkpoint_layouts(tmp_path, prefix):
    """Both published layouts load bit-exactly: bare encoder./decoder.
    (standalone files) and first_stage_model.* (full checkpoints)."""
    import safetensors.numpy as st

    donor = pl.load_vae("tiny-vae", seed=3)
    flat = flatten_params(jax.device_get(donor.params["vae"]))
    state_dict = sdc.synthesize_state_dict(
        flat, sdc.vae_schedule(get_config("tiny-vae"), prefix=prefix)
    )
    path = tmp_path / "vae.safetensors"
    # synthesize emits transposed views; safetensors serializes the
    # raw buffer, so real writers (and this fixture) must make them
    # contiguous first
    st.save_file(
        {k: np.ascontiguousarray(v) for k, v in state_dict.items()},
        str(path),
    )

    loaded = pl.load_vae("tiny-vae", checkpoint=str(path), seed=0)
    got = flatten_params(jax.device_get(loaded.params["vae"]))
    for key in flat:
        np.testing.assert_array_equal(got[key], flat[key], err_msg=key)


def test_load_vae_rejects_non_vae_names():
    with pytest.raises(ValueError, match="not an image-VAE"):
        pl.load_vae("tiny-unet")


def test_usdu_node_uses_standalone_vae():
    """UltimateSDUpscaleDistributed must actually USE a VAELoader
    replacement (not silently keep the bundled VAE): different VAE
    weights -> different output."""
    from comfyui_distributed_tpu.graph.nodes_upscale import (
        UltimateSDUpscaleDistributed,
    )

    bundle = pl.load_pipeline("tiny-unet", seed=0)
    other = pl.load_vae("tiny-vae", seed=99)  # different weights
    img = jnp.asarray(
        np.linspace(0, 1, 64 * 64 * 3, dtype=np.float32).reshape(1, 64, 64, 3)
    )
    pos = pl.encode_text_pooled(bundle, ["p"])
    neg = pl.encode_text_pooled(bundle, [""])
    kwargs = dict(
        seed=1, steps=2, cfg=7.0, sampler_name="euler",
        scheduler="karras", denoise=0.4, upscale_by=2.0, tile_width=64,
        tile_height=64, tile_padding=16,
    )
    (base,) = UltimateSDUpscaleDistributed().run(
        img, bundle, pos, neg, bundle, **kwargs
    )
    (swapped,) = UltimateSDUpscaleDistributed().run(
        img, bundle, pos, neg, other, **kwargs
    )
    assert base.shape == swapped.shape
    assert not np.array_equal(np.asarray(base), np.asarray(swapped))


def test_vae_loader_node_plugs_into_decode():
    from comfyui_distributed_tpu.graph.nodes_core import (
        VAEDecode,
        VAEEncode,
        VAELoader,
    )

    (vb,) = VAELoader().load_vae("tiny-vae")
    img = jnp.full((1, 32, 32, 3), 0.25)
    (latent,) = VAEEncode().encode(img, vb)
    (out,) = VAEDecode().decode(latent, vb)
    assert out.shape == img.shape
    assert np.isfinite(np.asarray(out)).all()
