"""Driver contract: dryrun_multichip executes a sharded train step on
the virtual mesh, and every bundled workflow validates against the
node registry (schema drift guard)."""

import json
import os

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_8():
    import sys

    sys.path.insert(0, REPO_ROOT)
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)  # asserts finite loss internally


def test_dryrun_multichip_odd_count():
    import __graft_entry__ as graft

    graft.dryrun_multichip(1)


@pytest.mark.parametrize(
    "name",
    sorted(
        f
        for f in os.listdir(os.path.join(REPO_ROOT, "workflows"))
        if f.endswith(".json")
    ),
)
def test_bundled_workflows_validate(name):
    from comfyui_distributed_tpu.graph import validate_prompt

    with open(os.path.join(REPO_ROOT, "workflows", name)) as fh:
        prompt = json.load(fh)
    validate_prompt(prompt)
