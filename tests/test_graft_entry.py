"""Driver contract: dryrun_multichip executes a sharded train step on
the virtual mesh, and every bundled workflow validates against the
node registry (schema drift guard)."""

import json
import os

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_8():
    """The full 8-device dryrun (train + generate + USDU + batched) —
    asserts finite loss and parity internally. Runs in a SUBPROCESS
    with the inherited (conftest) env: deep into the full suite the
    XLA CPU compiler segfaults compiling heavy shard_map programs
    under the parent's accumulated compiler state (r5: reproducible —
    the crash point follows wherever the first in-process dryrun
    lands; never reproduces in a fresh process, which is also how the
    driver invokes dryrun_multichip)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip(8)" in proc.stdout
    assert "usdu tile_batch=2 ok" in proc.stdout


def test_dryrun_multichip_odd_count():
    """n=1 (no even split -> model axis collapses). Runs in a
    SUBPROCESS: deep into the full suite the parent carries hundreds
    of compiled programs, and the XLA CPU compiler segfaulted
    compiling this 1-device shard_map program under that accumulated
    state (r5: reproducible at the same suite position, never in
    isolation). A fresh process is also how the driver invokes
    dryrun_multichip."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(1)"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip(1)" in proc.stdout


def test_dryrun_multichip_clean_env_subprocess():
    """The driver-environment contract: with NO XLA_FLAGS and NO
    JAX_PLATFORMS in the env (and a possibly-wedged TPU plugin
    present), dryrun_multichip must pin the CPU backend itself and
    provision its own virtual devices (round-1 regression: rc=124)."""
    import subprocess
    import sys

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    # generous hang-guard: under a full-suite run on a 1-core box the
    # subprocess compile contends with the parent's and can exceed the
    # isolated ~200s runtime by 2-3x
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(8)"],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=540,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip(8)" in proc.stdout
    # throughput ledger (VERDICT r4 item 3): the tail must carry a
    # parseable per-phase timing line for the round-over-round table
    tail = [
        line for line in proc.stdout.splitlines() if ": timings " in line
    ]
    assert tail, proc.stdout[-1500:]
    timings = json.loads(tail[-1].split(": timings ", 1)[1])
    for key in (
        "train_first_s", "train_again_s", "generate_first_s",
        "generate_again_s", "usdu_single_s", "usdu_sharded_s",
        "usdu_sharded_again_s", "usdu_batched_s", "total_s",
    ):
        assert key in timings and timings[key] >= 0, key
    # cached re-execution must be faster than compile+run
    assert timings["train_again_s"] < timings["train_first_s"]


@pytest.mark.parametrize(
    "name",
    sorted(
        f
        for f in os.listdir(os.path.join(REPO_ROOT, "workflows"))
        if f.endswith(".json")
    ),
)
def test_bundled_workflows_validate(name):
    from comfyui_distributed_tpu.graph import validate_prompt

    with open(os.path.join(REPO_ROOT, "workflows", name)) as fh:
        prompt = json.load(fh)
    validate_prompt(prompt)
