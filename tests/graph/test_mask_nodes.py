"""Mask node set (ComfyUI substrate parity: SolidMask, InvertMask,
CropMask, MaskToImage, ImageToMask, MaskComposite, FeatherMask,
GrowMask, ImageCompositeMasked, LatentCompositeMasked).

Numeric oracles are independent numpy re-derivations of the host
stack's loop semantics (per-column feather ramps, iterated 3x3 grey
morphology), not calls into the implementation under test.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_mask import (
    CropMask,
    FeatherMask,
    GrowMask,
    ImageCompositeMasked,
    ImageToMask,
    InvertMask,
    LatentCompositeMasked,
    MaskComposite,
    MaskToImage,
    SolidMask,
    as_mask,
    composite,
)

pytestmark = pytest.mark.fast


def test_solid_and_invert():
    (m,) = SolidMask().solid(value=0.25, width=8, height=4)
    assert m.shape == (1, 4, 8)
    np.testing.assert_allclose(np.asarray(m), 0.25)
    (inv,) = InvertMask().invert(m)
    np.testing.assert_allclose(np.asarray(inv), 0.75)


def test_as_mask_normalizes_rank():
    assert as_mask(np.zeros((4, 6))).shape == (1, 4, 6)
    assert as_mask(np.zeros((2, 4, 6))).shape == (2, 4, 6)
    assert as_mask(np.zeros((2, 4, 6, 1))).shape == (2, 4, 6)


def test_crop_mask_clamps():
    m = jnp.arange(64, dtype=jnp.float32).reshape(1, 8, 8) / 64.0
    (c,) = CropMask().crop(m, x=5, y=6, width=10, height=10)
    assert c.shape == (1, 2, 3)
    np.testing.assert_array_equal(np.asarray(c), np.asarray(m)[:, 6:, 5:])


def test_mask_image_roundtrip():
    m = jnp.linspace(0, 1, 12).reshape(1, 3, 4)
    (img,) = MaskToImage().mask_to_image(m)
    assert img.shape == (1, 3, 4, 3)
    (back,) = ImageToMask().image_to_mask(img, channel="green")
    np.testing.assert_allclose(np.asarray(back), np.asarray(m))
    with pytest.raises(ValueError):
        ImageToMask().image_to_mask(img, channel="alpha")
    with pytest.raises(ValueError):
        ImageToMask().image_to_mask(img, channel="luma")


@pytest.mark.parametrize(
    "op,dest_v,src_v,expect",
    [
        ("multiply", 0.5, 0.5, 0.25),
        ("add", 0.75, 0.75, 1.0),      # clamps at 1.0
        ("subtract", 0.25, 0.75, 0.0),  # clamps at 0.0
        ("and", 1.0, 1.0, 1.0),
        ("and", 1.0, 0.0, 0.0),
        ("or", 1.0, 0.0, 1.0),
        ("xor", 1.0, 0.0, 1.0),
        ("xor", 1.0, 1.0, 0.0),
    ],
)
def test_mask_composite_ops_full_overlap(op, dest_v, src_v, expect):
    dest = jnp.full((1, 4, 4), dest_v)
    src = jnp.full((1, 4, 4), src_v)
    (out,) = MaskComposite().combine(dest, src, operation=op)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_mask_composite_offset_keeps_outside():
    dest = jnp.zeros((1, 6, 6))
    src = jnp.ones((1, 3, 3))
    (out,) = MaskComposite().combine(dest, src, x=4, y=4, operation="add")
    arr = np.asarray(out)
    # only the 2x2 clipped overlap changes
    assert arr[:, 4:, 4:].min() == 1.0
    assert arr.sum() == 4.0


def test_mask_composite_rejects_unknown_op():
    m = jnp.zeros((1, 2, 2))
    with pytest.raises(ValueError):
        MaskComposite().combine(m, m, operation="divide")


def test_feather_matches_loop_semantics():
    h, w, left, top, right, bottom = 7, 9, 3, 2, 4, 0
    base = np.random.default_rng(0).random((1, h, w)).astype(np.float32)
    expected = base.copy()
    for x in range(left):
        expected[:, :, x] *= (x + 1) / left
    for x in range(right):
        expected[:, :, -x - 1] *= (x + 1) / right
    for y in range(top):
        expected[:, y, :] *= (y + 1) / top
    for y in range(bottom):
        expected[:, -y - 1, :] *= (y + 1) / bottom
    (out,) = FeatherMask().feather(
        jnp.asarray(base), left=left, top=top, right=right, bottom=bottom
    )
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-6)


def _np_morph(mask: np.ndarray, n: int, tapered: bool) -> np.ndarray:
    """Oracle: iterated 3x3 grey morphology with edge-clamped borders."""
    grow = n > 0
    m = mask.copy()
    for _ in range(abs(n)):
        pad = np.pad(m, ((0, 0), (1, 1), (1, 1)), mode="edge")
        out = np.empty_like(m)
        h, w = m.shape[1:]
        for y in range(h):
            for x in range(w):
                win = pad[:, y : y + 3, x : x + 3]
                if tapered:
                    vals = np.stack(
                        [win[:, 1, 1], win[:, 0, 1], win[:, 2, 1],
                         win[:, 1, 0], win[:, 1, 2]]
                    )
                else:
                    vals = win.reshape(win.shape[0], -1).T
                out[:, y, x] = vals.max(0) if grow else vals.min(0)
        m = out
    return m


@pytest.mark.parametrize("expand", [2, -1])
@pytest.mark.parametrize("tapered", [True, False])
def test_grow_mask_matches_morphology_oracle(expand, tapered):
    rng = np.random.default_rng(1)
    base = (rng.random((2, 9, 11)) > 0.6).astype(np.float32)
    (out,) = GrowMask().expand_mask(
        jnp.asarray(base), expand=expand, tapered_corners=tapered
    )
    np.testing.assert_allclose(
        np.asarray(out), _np_morph(base, expand, tapered), rtol=1e-6
    )


def test_grow_mask_diamond_vs_square():
    base = np.zeros((1, 7, 7), np.float32)
    base[0, 3, 3] = 1.0
    (diamond,) = GrowMask().expand_mask(jnp.asarray(base), expand=2,
                                        tapered_corners=True)
    (square,) = GrowMask().expand_mask(jnp.asarray(base), expand=2,
                                       tapered_corners=False)
    d, s = np.asarray(diamond), np.asarray(square)
    assert d[0, 1, 1] == 0.0 and s[0, 1, 1] == 1.0  # corner of the 5x5
    assert d[0, 1, 3] == 1.0 and d[0, 3, 1] == 1.0  # diamond tips


def test_image_composite_masked_blend_and_clip():
    dest = jnp.zeros((1, 8, 8, 3))
    src = jnp.ones((1, 4, 4, 3))
    mask = jnp.full((1, 4, 4), 0.5)
    (out,) = ImageCompositeMasked().composite(
        dest, src, x=6, y=6, mask=mask
    )
    arr = np.asarray(out)
    np.testing.assert_allclose(arr[0, 6:, 6:], 0.5)
    assert arr[0, :6].max() == 0.0 and arr[0, :, :6].max() == 0.0


def test_image_composite_negative_offset():
    dest = jnp.zeros((1, 6, 6, 1))
    src = jnp.ones((1, 4, 4, 1))
    (out,) = ImageCompositeMasked().composite(dest, src, x=-2, y=-2)
    arr = np.asarray(out)[..., 0]
    assert arr[0, :2, :2].min() == 1.0  # bottom-right quarter of src lands
    assert arr[0, 2:, :].max() == 0.0 and arr[0, :, 2:].max() == 0.0


def test_image_composite_resize_source():
    dest = jnp.zeros((1, 8, 8, 3))
    src = jnp.ones((1, 2, 2, 3))
    (out,) = ImageCompositeMasked().composite(
        dest, src, resize_source=True
    )
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_composite_batch_broadcast():
    dest = jnp.zeros((3, 4, 4, 2))
    src = jnp.ones((1, 4, 4, 2))
    out = composite(dest, src, 0, 0)
    assert out.shape == (3, 4, 4, 2)
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_composite_batched_mask_over_singleton_images():
    dest = jnp.zeros((1, 4, 4, 3))
    src = jnp.ones((1, 4, 4, 3))
    mask = jnp.stack([jnp.zeros((4, 4)), jnp.ones((4, 4))])
    (out,) = ImageCompositeMasked().composite(dest, src, mask=mask)
    arr = np.asarray(out)
    assert arr.shape == (2, 4, 4, 3)
    np.testing.assert_allclose(arr[0], 0.0)
    np.testing.assert_allclose(arr[1], 1.0)


def test_feather_oversized_width_clamps_to_extent():
    m = jnp.ones((1, 4, 4))
    (out,) = FeatherMask().feather(m, left=8)
    # left clamps to width 4: columns scale (i+1)/4, reaching 1.0
    np.testing.assert_allclose(
        np.asarray(out)[0, 0], [0.25, 0.5, 0.75, 1.0], rtol=1e-6
    )


def test_grow_mask_traced_size_constant_in_expand():
    import jax

    base = jnp.zeros((1, 8, 8))

    def run(expand):
        return jax.make_jaxpr(
            lambda m: GrowMask().expand_mask(m, expand=expand)[0]
        )(base)

    # fori_loop keeps the op count flat as expand grows
    assert len(str(run(64)).splitlines()) == len(str(run(2)).splitlines())


def test_latent_composite_masked_pixel_units():
    dest = {"samples": jnp.zeros((1, 8, 8, 4))}
    src = {"samples": jnp.ones((1, 4, 4, 4))}
    # x=16 px → 2 latent cells
    (out,) = LatentCompositeMasked().composite(dest, src, x=16, y=16)
    arr = np.asarray(out["samples"])
    np.testing.assert_allclose(arr[0, 2:6, 2:6], 1.0)
    assert arr[0, :2].max() == 0.0
    # untouched keys survive
    dest2 = {"samples": jnp.zeros((1, 4, 4, 4)), "width": 32}
    (out2,) = LatentCompositeMasked().composite(dest2, src, x=0, y=0)
    assert out2["width"] == 32
