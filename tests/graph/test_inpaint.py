"""Inpainting substrate: VAEEncodeForInpaint / SetLatentNoiseMask /
mask-aware KSampler (the ComfyUI-substrate nodes the reference's
users rely on for inpaint workflows)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    KSampler,
    SeedSpec,
    SetLatentNoiseMask,
    VAEEncodeForInpaint,
)
from comfyui_distributed_tpu.models import pipeline as pl

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    return pl.load_pipeline("tiny-unet", seed=0)


def _cond(bundle):
    return (
        pl.encode_text_pooled(bundle, ["p"]),
        pl.encode_text_pooled(bundle, [""]),
    )


def test_masked_region_only_changes(bundle):
    """The unmasked half survives full-denoise sampling bit-exactly;
    the masked half is regenerated."""
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, :, 4:] = 1.0
    latent = {"samples": z, "noise_mask": jnp.asarray(mask)[..., None]}
    pos, neg = _cond(bundle)
    (out,) = KSampler().sample(
        bundle, 3, 2, 1.0, "euler", "karras", pos, neg, latent, denoise=1.0
    )
    got = np.asarray(out["samples"])
    np.testing.assert_array_equal(got[:, :, :4], np.asarray(z)[:, :, :4])
    assert not np.allclose(got[:, :, 4:], np.asarray(z)[:, :, 4:])
    # a bare [B,H,W] MASK layout (LoadImage convention) behaves the same
    latent3 = {"samples": z, "noise_mask": jnp.asarray(mask)}
    (out3,) = KSampler().sample(
        bundle, 3, 2, 1.0, "euler", "karras", pos, neg, latent3, denoise=1.0
    )
    np.testing.assert_array_equal(np.asarray(out3["samples"]), got)


def test_vae_encode_for_inpaint(bundle):
    img = jnp.full((1, 32, 32, 3), 0.25)
    mask = np.zeros((32, 32), np.float32)
    mask[8:16, 8:16] = 1.0
    (latent,) = VAEEncodeForInpaint().encode(img, bundle, jnp.asarray(mask))
    z = latent["samples"]
    side = 32 // bundle.latent_scale
    assert z.shape[1:3] == (side, side)
    nm = np.asarray(latent["noise_mask"])
    assert nm.shape == (1, side, side, 1)
    assert nm.max() == 1.0 and nm.min() == 0.0
    # grow_mask_by dilates: the latent mask covers more area than the
    # bare 8x8 square would at latent resolution
    assert nm.sum() > (8 // bundle.latent_scale) ** 2


def test_grow_mask_dilates_noise_mask_only(bundle):
    """grow_mask_by must not enlarge the gray-neutralized pixel region
    (reference neutralizes with the un-grown rounded mask and dilates
    only the emitted noise_mask, g x g kernel — ADVICE r4): the encoded
    samples are identical across grow settings, the noise_mask is not."""
    rng = np.random.default_rng(3)
    img = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    mask = np.zeros((32, 32), np.float32)
    mask[12:20, 12:20] = 1.0
    (l0,) = VAEEncodeForInpaint().encode(
        img, bundle, jnp.asarray(mask), grow_mask_by=0
    )
    (l6,) = VAEEncodeForInpaint().encode(
        img, bundle, jnp.asarray(mask), grow_mask_by=6
    )
    np.testing.assert_array_equal(
        np.asarray(l0["samples"]), np.asarray(l6["samples"])
    )
    assert np.asarray(l6["noise_mask"]).sum() > np.asarray(
        l0["noise_mask"]
    ).sum()


def test_set_latent_noise_mask():
    z = jnp.zeros((1, 8, 8, 4))
    (out,) = SetLatentNoiseMask().set_mask(
        {"samples": z}, jnp.ones((1, 64, 64))
    )
    assert out["noise_mask"].shape == (1, 8, 8, 1)
    np.testing.assert_allclose(np.asarray(out["noise_mask"]), 1.0, atol=1e-6)
    # a mask round-tripped through another latent's noise_mask
    # ([B,H,W,1]) must normalize too
    (out2,) = SetLatentNoiseMask().set_mask(
        {"samples": z}, jnp.ones((1, 64, 64, 1))
    )
    assert out2["noise_mask"].shape == (1, 8, 8, 1)


def test_chained_inpaint_keeps_mask(bundle):
    """base + refine pattern: the KSampler output latent dict carries
    noise_mask forward so a second pass stays masked (common_ksampler
    parity)."""
    rng = np.random.default_rng(2)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, :, 4:] = 1.0
    latent = {"samples": z, "noise_mask": jnp.asarray(mask)[..., None]}
    pos, neg = _cond(bundle)
    (first,) = KSampler().sample(
        bundle, 3, 2, 1.0, "euler", "karras", pos, neg, latent, denoise=1.0
    )
    assert "noise_mask" in first
    (second,) = KSampler().sample(
        bundle, 4, 2, 1.0, "euler", "karras", pos, neg, first, denoise=0.5
    )
    got = np.asarray(second["samples"])
    np.testing.assert_array_equal(got[:, :, :4], np.asarray(z)[:, :, :4])


def test_image_pad_for_outpaint():
    from comfyui_distributed_tpu.graph.nodes_core import ImagePadForOutpaint

    img = jnp.full((1, 32, 32, 3), 0.5)
    (padded, mask) = ImagePadForOutpaint().expand(
        img, left=0, top=0, right=16, bottom=0, feathering=8
    )
    assert padded.shape == (1, 32, 48, 3)
    assert mask.shape == (1, 32, 48)
    m = np.asarray(mask)
    np.testing.assert_array_equal(m[:, :, 32:], 1.0)  # new region
    np.testing.assert_array_equal(m[:, :, :24], 0.0)  # deep original
    # feather ramp rises toward the new edge
    assert 0.0 < m[0, 16, 28] < m[0, 16, 31] <= 1.0
    # edge-replicated padding
    np.testing.assert_array_equal(np.asarray(padded)[:, :, 32:], 0.5)


def test_mesh_inpaint_preserves_unmasked(bundle):
    """The mask rides through the shard_map mesh path: every
    participant's output keeps the unmasked half bit-exactly."""
    from types import SimpleNamespace

    from comfyui_distributed_tpu.parallel import build_mesh

    mesh = build_mesh({"data": 8})
    ctx = SimpleNamespace(mesh=mesh)
    rng = np.random.default_rng(1)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, 4:] = 1.0
    latent = {"samples": z, "noise_mask": jnp.asarray(mask)[..., None]}
    pos, neg = _cond(bundle)
    (out,) = KSampler().sample(
        bundle, SeedSpec(base_seed=5, per_participant=True), 2, 1.0,
        "euler", "karras", pos, neg, latent, denoise=1.0, context=ctx,
    )
    got = np.asarray(out["samples"])  # [8, 8, 8, 4] participant-major
    assert got.shape[0] == 8
    for i in range(8):
        np.testing.assert_array_equal(
            got[i, :4], np.asarray(z)[0, :4], err_msg=f"participant {i}"
        )
    # participants differ in the regenerated half (distinct seeds)
    assert not np.allclose(got[0, 4:], got[1, 4:])
