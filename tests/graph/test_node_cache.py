"""Cross-run node caching: unchanged subgraphs skip re-execution;
input changes invalidate downstream; distributed nodes never cache."""

import numpy as np

from comfyui_distributed_tpu.graph import ExecutionContext, GraphExecutor
from comfyui_distributed_tpu.graph.registry import register_node


@register_node
class _CountingNode:
    CALLS = 0

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"value": ("INT", {"default": 1})}}

    RETURN_TYPES = ("INT",)
    FUNCTION = "run"

    def run(self, value, context=None):
        _CountingNode.CALLS += 1
        return (int(value) * 2,)


@register_node
class _CountingSink:
    CALLS = 0

    @classmethod
    def INPUT_TYPES(cls):
        return {"required": {"value": ("INT",)}}

    RETURN_TYPES = ()
    FUNCTION = "run"
    OUTPUT_NODE = True

    def run(self, value, context=None):
        _CountingSink.CALLS += 1
        return ({"ui": {"value": value}},)


def _prompt(value=3):
    return {
        "1": {"class_type": "_CountingNode", "inputs": {"value": value}},
        "2": {"class_type": "_CountingSink", "inputs": {"value": ["1", 0]}},
    }


def test_unchanged_node_cached_across_runs():
    _CountingNode.CALLS = 0
    _CountingSink.CALLS = 0
    ctx = ExecutionContext()
    executor = GraphExecutor(ctx)
    out1 = executor.execute(_prompt())
    out2 = executor.execute(_prompt())
    assert _CountingNode.CALLS == 1          # cached second time
    assert _CountingSink.CALLS == 2          # output sinks always run
    assert executor.last_timings["1"] == 0.0
    assert out1["2"][0]["ui"]["value"] == out2["2"][0]["ui"]["value"] == 6


def test_literal_change_invalidates():
    _CountingNode.CALLS = 0
    ctx = ExecutionContext()
    executor = GraphExecutor(ctx)
    executor.execute(_prompt(3))
    executor.execute(_prompt(4))
    assert _CountingNode.CALLS == 2


def test_upstream_change_invalidates_downstream():
    @register_node
    class _CountingMid:
        CALLS = 0

        @classmethod
        def INPUT_TYPES(cls):
            return {"required": {"value": ("INT",)}}

        RETURN_TYPES = ("INT",)
        FUNCTION = "run"

        def run(self, value, context=None):
            _CountingMid.CALLS += 1
            return (value + 1,)

    prompt = {
        "1": {"class_type": "_CountingNode", "inputs": {"value": 3}},
        "m": {"class_type": "_CountingMid", "inputs": {"value": ["1", 0]}},
        "2": {"class_type": "_CountingSink", "inputs": {"value": ["m", 0]}},
    }
    ctx = ExecutionContext()
    executor = GraphExecutor(ctx)
    executor.execute(prompt)
    assert _CountingMid.CALLS == 1
    prompt2 = {**prompt, "1": {"class_type": "_CountingNode", "inputs": {"value": 9}}}
    executor.execute(prompt2)
    assert _CountingMid.CALLS == 2  # upstream change rippled down


def test_distributed_nodes_never_cache():
    from comfyui_distributed_tpu.graph.nodes_distributed import DistributedCollector
    from comfyui_distributed_tpu.graph.nodes_upscale import (
        UltimateSDUpscaleDistributed,
    )

    assert DistributedCollector.NEVER_CACHE is True
    assert UltimateSDUpscaleDistributed.NEVER_CACHE is True
