"""Conditioning-mutating nodes: FluxGuidance and ReferenceLatent
(clone semantics — graph branches must not see each other's edits)."""

import jax.numpy as jnp
import pytest

from comfyui_distributed_tpu.graph.nodes_controlnet import (
    FluxGuidance,
    ReferenceLatent,
)

pytestmark = pytest.mark.slow


def test_flux_guidance_sets_scale():
    ctx = jnp.zeros((1, 4, 8))
    (c,) = FluxGuidance().append(ctx, 2.5)
    assert c.guidance == 2.5
    # restamping yields a new value without mutating the input
    (c2,) = FluxGuidance().append(c, 4.0)
    assert c2.guidance == 4.0
    assert c.guidance == 2.5


def test_reference_latent_appends_without_mutation():
    ctx = jnp.zeros((1, 4, 8))
    (c1,) = ReferenceLatent().append(ctx, {"samples": jnp.ones((1, 4, 4, 16))})
    assert len(c1.reference_latents) == 1
    (c2,) = ReferenceLatent().append(
        c1, {"samples": jnp.zeros((1, 2, 2, 16))}
    )
    assert len(c2.reference_latents) == 2
    assert len(c1.reference_latents) == 1  # clone, not shared list
    assert c2.reference_latents[0] is c1.reference_latents[0]
