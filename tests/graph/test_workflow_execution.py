"""Execute the round-5 showcase workflows end-to-end at tiny scale:
the bundled JSON is loaded verbatim, then models/dims/steps shrink so
the full graph (patch node -> sampler -> decode -> collector -> save)
runs as one executor pass."""

import json
import os

import numpy as np
import pytest

from comfyui_distributed_tpu.graph.executor import (
    ExecutionContext,
    GraphExecutor,
)

pytestmark = pytest.mark.slow

WORKFLOW_DIR = os.path.join(
    os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ),
    "workflows",
)


def _load(name):
    with open(os.path.join(WORKFLOW_DIR, name)) as fh:
        return json.load(fh)


def test_pag_workflow_executes_tiny(tmp_path, monkeypatch):
    monkeypatch.setenv("CDT_OUTPUT_DIR", str(tmp_path))
    g = _load("distributed-txt2img-pag.json")
    g["1"]["inputs"]["ckpt_name"] = "tiny-unet"
    g["5"]["inputs"].update({"width": 64, "height": 64})
    g["7"]["inputs"].update({"steps": 2})
    outputs = GraphExecutor(ExecutionContext()).execute(g)
    files = [f for f in os.listdir(tmp_path) if f.startswith("pag_")]
    assert files, "SaveImage wrote nothing"
    assert outputs


def test_flux_dual_prompt_workflow_executes_tiny(tmp_path, monkeypatch):
    monkeypatch.setenv("CDT_OUTPUT_DIR", str(tmp_path))
    g = _load("distributed-flux-dual-prompt.json")
    g["1"]["inputs"]["unet_name"] = "tiny-flux"
    g["2"]["inputs"].update(
        {"clip_name1": "tiny-te", "clip_name2": "tiny-t5"}
    )
    g["3"]["inputs"]["vae_name"] = "tiny-vae-flux"
    g["5"]["inputs"].update({"width": 32, "height": 32})
    g["10"]["inputs"].update({"steps": 2})
    outputs = GraphExecutor(ExecutionContext()).execute(g)
    files = [f for f in os.listdir(tmp_path) if f.startswith("flux-dual_")]
    assert files, "SaveImage wrote nothing"
    assert outputs
