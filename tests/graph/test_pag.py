"""PerturbedAttentionGuidance: identity self-attention perturbation,
the pag_cfg_model composition, and the node's family guard."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import KSampler
from comfyui_distributed_tpu.graph.nodes_loaders import (
    PerturbedAttentionGuidance,
)
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.ops import samplers as smp

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(11)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


@pytest.mark.fast
def test_identity_attention_is_projected_v():
    from comfyui_distributed_tpu.models.layers import AttentionBlock

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(1, 6, 8)).astype(np.float32)
    )
    blk = AttentionBlock(2, 4, jnp.float32, identity_self=True)
    params = blk.init(jax.random.key(0), x)
    out = blk.apply(params, x)
    # manual: out = to_out(to_v(x)) with no attention mixing
    v = x @ params["params"]["to_v"]["kernel"]
    ref = (
        v @ params["params"]["to_out"]["kernel"]
        + params["params"]["to_out"]["bias"]
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # normal attention differs (mixing across tokens)
    normal = AttentionBlock(2, 4, jnp.float32).apply(params, x)
    assert not np.allclose(np.asarray(normal), np.asarray(out), atol=1e-4)


@pytest.mark.fast
def test_pag_cfg_model_math():
    base = lambda x, sigma, cond: cond  # noqa: E731
    pert = lambda x, sigma, cond: cond * 0.5  # noqa: E731
    x = jnp.zeros((1, 2, 2, 1))
    sig = jnp.ones((1,))
    pos = jnp.full_like(x, 2.0)
    neg = jnp.full_like(x, 1.0)
    guided = smp.pag_cfg_model(base, pert, 3.0, 2.0)
    out = guided(x, sig, (pos, neg))
    cfg = 1.0 + 3.0 * (2.0 - 1.0)  # 4.0
    expect = cfg + 2.0 * (2.0 - 1.0)  # + scale*(eps_pos - eps_pert)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_pag_zero_scale_equals_plain_cfg(bundle):
    pos = pl.encode_text(bundle, ["a castle"])
    neg = pl.encode_text(bundle, [""])
    x = jnp.asarray(
        np.random.default_rng(3).normal(size=(1, 8, 8, 4)).astype(np.float32)
    )
    sig = jnp.full((1,), 5.0)
    g_plain = pl.guided_model(bundle, bundle.params, 4.0)
    (patched,) = PerturbedAttentionGuidance().patch(bundle, scale=0.0)
    g_pag = pl.guided_model(patched, patched.params, 4.0)
    np.testing.assert_allclose(
        np.asarray(g_pag(x, sig, (pos, neg))),
        np.asarray(g_plain(x, sig, (pos, neg))),
        atol=1e-5,
    )
    # nonzero scale changes the prediction
    (p2,) = PerturbedAttentionGuidance().patch(bundle, scale=3.0)
    g2 = pl.guided_model(p2, p2.params, 4.0)
    assert not np.allclose(
        np.asarray(g2(x, sig, (pos, neg))),
        np.asarray(g_plain(x, sig, (pos, neg))),
        atol=1e-5,
    )


def test_pag_ksampler_end_to_end(bundle):
    (patched,) = PerturbedAttentionGuidance().patch(bundle, scale=2.5)
    latent = {"samples": jnp.zeros((1, 8, 8, 4))}
    pos = pl.encode_text(bundle, ["a castle"])
    neg = pl.encode_text(bundle, [""])
    (out,) = KSampler().sample(
        patched, 3, 2, 4.0, "euler", "karras", pos, neg, latent
    )
    arr = np.asarray(out["samples"])
    assert np.isfinite(arr).all()


@pytest.mark.fast
def test_pag_rejects_dit_families_and_combos():
    b = object.__new__(pl.PipelineBundle)
    b.model_name = "tiny-sd3"
    with pytest.raises(ValueError, match="DiT"):
        PerturbedAttentionGuidance().patch(b)
    # combos rejected at guided_model
    b2 = object.__new__(pl.PipelineBundle)
    b2.model_name = "tiny-unet"
    b2.cfg_rescale = 0.7
    b2.slg = None
    b2.dual_cfg = None
    b2.pag = pl.PAGSpec(scale=1.0)
    with pytest.raises(ValueError, match="combine"):
        pl.guided_model(b2, {}, 1.0)
    # patch-time rejection: the second patch node fails at graph build
    b3 = object.__new__(pl.PipelineBundle)
    b3.model_name = "tiny-unet"
    b3.slg = None
    b3.cfg_rescale = 0.7
    b3.dual_cfg = None
    b3.pag = None
    with pytest.raises(ValueError, match="RescaleCFG"):
        PerturbedAttentionGuidance().patch(b3)
