"""Custom-sampling cluster (KSamplerSelect / schedulers / noise /
guiders / SamplerCustom(-Advanced) / sigma utilities): the decomposed
sampling surface standard Flux/SD3 workflows are built from."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    EmptyLatentImage,
    KSampler,
    SeedSpec,
)
from comfyui_distributed_tpu.graph.nodes_custom_sampling import (
    BasicGuider,
    BasicScheduler,
    CFGGuider,
    DisableNoise,
    ExponentialScheduler,
    FlipSigmas,
    KarrasScheduler,
    KSamplerSelect,
    RandomNoise,
    SamplerCustom,
    SamplerCustomAdvanced,
    SplitSigmas,
    SplitSigmasDenoise,
)
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.ops import samplers as smp

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    """tiny-unet with zero-init leaves perturbed (see
    test_ksampler_advanced.bundle: zero-init out_conv ⇒ eps == 0 ⇒
    trajectories never move, trivializing every comparison)."""
    import jax

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(123)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


def _cond(bundle):
    return (
        pl.encode_text_pooled(bundle, ["p"]),
        pl.encode_text_pooled(bundle, [""]),
    )


# --- schedulers / sigma utilities (fast math, no model) ------------------

def test_basic_scheduler_matches_model_sigmas(bundle):
    (sig,) = BasicScheduler().get_sigmas(bundle, "karras", 6, 1.0)
    np.testing.assert_array_equal(
        np.asarray(sig), np.asarray(smp.get_sigmas("karras", 6))
    )


def test_basic_scheduler_denoise_zero_is_empty(bundle):
    (sig,) = BasicScheduler().get_sigmas(bundle, "karras", 6, 0.0)
    assert sig.shape == (0,)


def test_karras_scheduler_formula():
    (sig,) = KarrasScheduler().get_sigmas(5, 10.0, 0.1, 7.0)
    s = np.asarray(sig)
    assert s.shape == (6,)
    assert s[0] == pytest.approx(10.0)
    assert s[4] == pytest.approx(0.1)
    assert s[5] == 0.0
    assert np.all(np.diff(s) < 0)


def test_exponential_scheduler_log_spacing():
    (sig,) = ExponentialScheduler().get_sigmas(4, 8.0, 1.0)
    s = np.asarray(sig)
    np.testing.assert_allclose(
        s[:-1], np.exp(np.linspace(np.log(8.0), np.log(1.0), 4)), rtol=1e-6
    )
    assert s[-1] == 0.0


def test_split_sigmas_shares_boundary_point():
    sig = jnp.asarray(np.linspace(10.0, 0.0, 9), jnp.float32)
    high, low = SplitSigmas().split(sig, 3)
    assert high.shape == (4,)
    assert low.shape == (6,)
    assert float(high[-1]) == float(low[0])


def test_split_sigmas_denoise():
    sig = jnp.asarray(np.linspace(10.0, 0.0, 11), jnp.float32)  # 10 steps
    high, low = SplitSigmasDenoise().split(sig, 0.3)  # keep last 3 steps
    assert low.shape == (4,)
    assert high.shape == (8,)
    assert float(high[-1]) == float(low[0])
    # fractional step counts round half-up (0.35 * 10 -> 4 kept steps),
    # matching the reference stack's resume point
    high, low = SplitSigmasDenoise().split(sig, 0.35)
    assert low.shape == (5,)
    assert high.shape == (7,)


def test_flip_sigmas_bumps_leading_zero():
    sig = jnp.asarray([10.0, 5.0, 0.0], jnp.float32)
    (flipped,) = FlipSigmas().flip(sig)
    f = np.asarray(flipped)
    assert f[0] == pytest.approx(1e-4)
    np.testing.assert_array_equal(f[1:], [5.0, 10.0])
    (empty,) = FlipSigmas().flip(jnp.zeros((0,), jnp.float32))
    assert empty.shape == (0,)


def test_ksampler_select_validates():
    (s,) = KSamplerSelect().get_sampler("euler")
    assert s.name == "euler"
    with pytest.raises(ValueError, match="unknown sampler"):
        KSamplerSelect().get_sampler("nope")


# --- sampling parity -----------------------------------------------------

def test_sampler_custom_matches_ksampler(bundle):
    """SamplerCustom fed KSampler's exact schedule walks the same
    trajectory (same seed → same noise → same euler steps)."""
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    (single,) = KSampler().sample(
        bundle, 5, 4, 7.0, "euler", "karras", pos, neg, el, denoise=1.0
    )
    (sig,) = BasicScheduler().get_sigmas(bundle, "karras", 4, 1.0)
    (samp,) = KSamplerSelect().get_sampler("euler")
    out, denoised = SamplerCustom().sample(
        bundle, True, 5, 7.0, pos, neg, samp, sig, el
    )
    np.testing.assert_allclose(
        np.asarray(out["samples"]), np.asarray(single["samples"]), atol=1e-5
    )
    # grid ends at 0 ⇒ the two outputs coincide
    np.testing.assert_array_equal(
        np.asarray(out["samples"]), np.asarray(denoised["samples"])
    )


def test_two_stage_split_matches_single(bundle):
    """RandomNoise + high half, then DisableNoise + low half (the
    SplitSigmas refine pattern) equals one full run."""
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    (sig,) = BasicScheduler().get_sigmas(bundle, "karras", 4, 1.0)
    (samp,) = KSamplerSelect().get_sampler("euler")
    single, _ = SamplerCustom().sample(
        bundle, True, 5, 7.0, pos, neg, samp, sig, el
    )
    high, low = SplitSigmas().split(sig, 2)
    (noise,) = RandomNoise().get_noise(5)
    (guider,) = CFGGuider().get_guider(bundle, pos, neg, 7.0)
    stage1, stage1_denoised = SamplerCustomAdvanced().sample(
        noise, guider, samp, high, el
    )
    # leftover noise ⇒ the denoised prediction is a different array
    assert not np.array_equal(
        np.asarray(stage1["samples"]), np.asarray(stage1_denoised["samples"])
    )
    (no_noise,) = DisableNoise().get_noise()
    stage2, _ = SamplerCustomAdvanced().sample(
        no_noise, guider, samp, low, stage1
    )
    np.testing.assert_allclose(
        np.asarray(stage2["samples"]), np.asarray(single["samples"]),
        atol=5e-2,
    )


def test_basic_guider_is_cfg_one(bundle):
    """BasicGuider (single cond) equals CFGGuider at cfg=1.0 — one
    model eval per step, the Flux-style guidance shape."""
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    (sig,) = BasicScheduler().get_sigmas(bundle, "normal", 3, 1.0)
    (samp,) = KSamplerSelect().get_sampler("euler")
    (noise,) = RandomNoise().get_noise(7)
    (basic,) = BasicGuider().get_guider(bundle, pos)
    (cfg1,) = CFGGuider().get_guider(bundle, pos, neg, 1.0)
    out_b, _ = SamplerCustomAdvanced().sample(noise, basic, samp, sig, el)
    out_c, _ = SamplerCustomAdvanced().sample(noise, cfg1, samp, sig, el)
    np.testing.assert_allclose(
        np.asarray(out_b["samples"]), np.asarray(out_c["samples"]), atol=1e-5
    )


def test_empty_sigmas_is_identity(bundle):
    rng = np.random.default_rng(4)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    pos, _ = _cond(bundle)
    (samp,) = KSamplerSelect().get_sampler("euler")
    (noise,) = RandomNoise().get_noise(1)
    (guider,) = BasicGuider().get_guider(bundle, pos)
    out, denoised = SamplerCustomAdvanced().sample(
        noise, guider, samp, jnp.zeros((0,), jnp.float32), {"samples": z}
    )
    np.testing.assert_array_equal(np.asarray(out["samples"]), np.asarray(z))
    np.testing.assert_array_equal(
        np.asarray(denoised["samples"]), np.asarray(z)
    )


def test_masked_custom_keeps_unmasked_region(bundle):
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, :, 4:] = 1.0
    latent = {"samples": z, "noise_mask": jnp.asarray(mask)[..., None]}
    pos, neg = _cond(bundle)
    (sig,) = BasicScheduler().get_sigmas(bundle, "karras", 4, 1.0)
    (samp,) = KSamplerSelect().get_sampler("euler")
    out, _ = SamplerCustom().sample(
        bundle, True, 3, 7.0, pos, neg, samp, sig, latent
    )
    got = np.asarray(out["samples"])
    np.testing.assert_array_equal(got[:, :, :4], np.asarray(z)[:, :, :4])
    assert not np.allclose(got[:, :, 4:], np.asarray(z)[:, :, 4:])
    assert "noise_mask" in out  # extras propagate


def test_mesh_parallel_custom(bundle):
    """DistributedSeed → RandomNoise → SamplerCustomAdvanced fans out
    one SPMD program with per-participant folded seeds."""
    from types import SimpleNamespace

    from comfyui_distributed_tpu.parallel import build_mesh

    mesh = build_mesh({"data": 8})
    ctx = SimpleNamespace(mesh=mesh)
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    (sig,) = BasicScheduler().get_sigmas(bundle, "karras", 4, 1.0)
    (samp,) = KSamplerSelect().get_sampler("euler")
    (noise,) = RandomNoise().get_noise(
        SeedSpec(base_seed=9, per_participant=True)
    )
    (guider,) = CFGGuider().get_guider(bundle, pos, neg, 7.0)
    out, denoised = SamplerCustomAdvanced().sample(
        noise, guider, samp, sig, el, context=ctx
    )
    got = np.asarray(out["samples"])
    assert got.shape[0] == 8
    assert out.get("participant_major")
    sums = {round(float(got[i].sum()), 4) for i in range(8)}
    assert len(sums) == 8  # distinct participants
    # grid ends at 0 ⇒ mesh path's shared denoised output is exact
    np.testing.assert_array_equal(
        got, np.asarray(denoised["samples"])
    )

    # leftover-noise grid on the mesh path: denoised_output must be
    # the x0 prediction, not a copy of the noisy output
    high, _low = SplitSigmas().split(sig, 2)
    out_h, den_h = SamplerCustomAdvanced().sample(
        noise, guider, samp, high, el, context=ctx
    )
    oh, dh = np.asarray(out_h["samples"]), np.asarray(den_h["samples"])
    assert oh.shape == dh.shape == (8,) + oh.shape[1:]
    assert not np.array_equal(oh, dh)
    np.testing.assert_allclose(
        dh,
        np.asarray(
            pl.denoised_prediction(
                bundle, out_h["samples"], pos, neg, 7.0, float(high[-1])
            )
        ),
        atol=1e-5,
    )


def test_denoised_prediction_matches_inline_branch(bundle):
    """pipeline.denoised_prediction (the mesh path's extra eval) and
    _custom_sigmas_jit's inline denoised branch compute the same x0."""
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    (sig,) = BasicScheduler().get_sigmas(bundle, "karras", 4, 1.0)
    high, _ = SplitSigmas().split(sig, 2)
    (samp,) = KSamplerSelect().get_sampler("euler")
    out, denoised = SamplerCustom().sample(
        bundle, True, 5, 7.0, pos, neg, samp, high, el
    )
    np.testing.assert_allclose(
        np.asarray(denoised["samples"]),
        np.asarray(
            pl.denoised_prediction(
                bundle, out["samples"], pos, neg, 7.0, float(high[-1])
            )
        ),
        atol=1e-5,
    )
