"""Utility nodes: VAEEncodeTiled, LatentFromBatch/LatentBatch,
ImageBlur/ImageSharpen, LoraLoaderModelOnly, and the inpaint-model
conditioning path (InpaintModelConditioning + 9-channel UNet)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    ImageBlur,
    ImageSharpen,
    InpaintModelConditioning,
    KSampler,
    LatentBatch,
    LatentFromBatch,
    VAEEncode,
    VAEEncodeTiled,
)
from comfyui_distributed_tpu.models import pipeline as pl

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def inpaint_bundle():
    import jax

    b = pl.load_pipeline("tiny-unet-inpaint", seed=0)
    rng = np.random.default_rng(123)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


def test_latent_from_batch_slices_with_mask():
    z = jnp.arange(4 * 8 * 8 * 4, dtype=jnp.float32).reshape(4, 8, 8, 4)
    mask = jnp.ones((4, 8, 8, 1))
    (out,) = LatentFromBatch().frombatch(
        {"samples": z, "noise_mask": mask}, 1, 2
    )
    assert out["samples"].shape == (2, 8, 8, 4)
    np.testing.assert_array_equal(np.asarray(out["samples"]), np.asarray(z[1:3]))
    assert out["noise_mask"].shape[0] == 2
    # out-of-range clamps
    (tail,) = LatentFromBatch().frombatch({"samples": z}, 10, 5)
    assert tail["samples"].shape[0] == 1


def test_latent_batch_resizes_second():
    z1 = jnp.zeros((1, 8, 8, 4))
    z2 = jnp.ones((2, 4, 4, 4))
    (out,) = LatentBatch().batch({"samples": z1}, {"samples": z2})
    assert out["samples"].shape == (3, 8, 8, 4)
    np.testing.assert_allclose(np.asarray(out["samples"][1:]), 1.0, atol=1e-5)


def test_blur_preserves_mean_and_smooths():
    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    (bl,) = ImageBlur().blur(img, 3, 2.0)
    assert bl.shape == img.shape
    # normalized kernel + reflect padding ⇒ mean approximately kept
    assert abs(float(bl.mean()) - float(img.mean())) < 1e-3
    # high-frequency energy drops
    def energy(a):
        return float(jnp.abs(jnp.diff(a, axis=1)).mean())
    assert energy(bl) < energy(img)
    # radius 0 is identity
    (same,) = ImageBlur().blur(img, 0, 2.0)
    assert same is img


def test_sharpen_increases_contrast():
    rng = np.random.default_rng(1)
    img = jnp.asarray(rng.uniform(0.2, 0.8, size=(1, 32, 32, 3)), jnp.float32)
    (sh,) = ImageSharpen().sharpen(img, 2, 1.0, 1.0)
    def energy(a):
        return float(jnp.abs(jnp.diff(a, axis=1)).mean())
    assert energy(sh) > energy(img)
    assert float(sh.min()) >= 0.0 and float(sh.max()) <= 1.0


def test_vae_encode_tiled_matches_full(inpaint_bundle):
    """Tiled encode equals full encode away from tile seams (exact in
    tile cores; feathered at boundaries)."""
    rng = np.random.default_rng(2)
    img = jnp.asarray(rng.uniform(size=(1, 128, 128, 3)), jnp.float32)
    (full,) = VAEEncode().encode(img, inpaint_bundle)
    (tiled,) = VAEEncodeTiled().encode_tiled(img, inpaint_bundle, 64)
    a, b = np.asarray(full["samples"]), np.asarray(tiled["samples"])
    assert a.shape == b.shape
    # agreement over most of the plane (seam feathering differs)
    close = np.isclose(a, b, atol=0.15).mean()
    assert close > 0.8


def test_inpaint_model_conditioning_shapes(inpaint_bundle):
    rng = np.random.default_rng(3)
    img = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    mask = np.zeros((1, 32, 32), np.float32)
    mask[:, 8:24, 8:24] = 1.0
    p = pl.encode_text_pooled(inpaint_bundle, ["fill"])
    n = pl.encode_text_pooled(inpaint_bundle, [""])
    p2, n2, lat = InpaintModelConditioning().encode(
        p, n, inpaint_bundle, img, jnp.asarray(mask)
    )
    # concat = mask (1) + masked-image latents (C)
    assert p2.concat_latent.shape[-1] == 1 + inpaint_bundle.latent_channels
    assert n2.concat_latent is not None
    assert "noise_mask" in lat
    # noise_mask=False omits the latent mask but keeps the concat
    _p3, _n3, lat2 = InpaintModelConditioning().encode(
        p, n, inpaint_bundle, img, jnp.asarray(mask), noise_mask=False
    )
    assert "noise_mask" not in lat2


def test_inpaint_conditioning_accepts_4d_mask(inpaint_bundle):
    """[B,H,W,1] MASK inputs (the codebase MASK contract) normalize
    like everywhere else instead of crashing the resize."""
    rng = np.random.default_rng(9)
    img = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    mask4d = jnp.ones((1, 16, 16, 1))
    p = pl.encode_text_pooled(inpaint_bundle, ["x"])
    n = pl.encode_text_pooled(inpaint_bundle, [""])
    p2, _n2, lat = InpaintModelConditioning().encode(
        p, n, inpaint_bundle, img, mask4d
    )
    assert p2.concat_latent.shape[-1] == 1 + inpaint_bundle.latent_channels
    assert lat["samples"].shape[1:3] == p2.concat_latent.shape[1:3]


def test_usdu_rejects_concat_conditioning(inpaint_bundle):
    from comfyui_distributed_tpu.ops import tiles as tile_ops
    from comfyui_distributed_tpu.ops import upscale as up

    cond = pl.encode_text_pooled(inpaint_bundle, ["x"])
    cond.concat_latent = jnp.zeros((1, 8, 8, 5))
    grid = tile_ops.calculate_tiles(64, 64, 32, 4)
    with pytest.raises(ValueError, match="concat conditioning"):
        up.prep_cond_for_tiles(cond, grid)


def test_inpaint_model_samples_nine_channels(inpaint_bundle):
    """The 9-channel UNet consumes concat conditioning through a full
    KSampler run; the unmasked region is pinned by the noise_mask."""
    rng = np.random.default_rng(4)
    img = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    mask = np.zeros((1, 32, 32), np.float32)
    mask[:, 16:] = 1.0
    p = pl.encode_text_pooled(inpaint_bundle, ["fill"])
    n = pl.encode_text_pooled(inpaint_bundle, [""])
    p2, n2, lat = InpaintModelConditioning().encode(
        p, n, inpaint_bundle, img, jnp.asarray(mask)
    )
    orig = np.asarray(lat["samples"])
    (out,) = KSampler().sample(
        inpaint_bundle, 3, 2, 7.0, "euler", "karras", p2, n2, lat
    )
    got = np.asarray(out["samples"])
    assert got.shape == orig.shape
    # the bilinear latent-mask resize feathers the boundary row; the
    # interior of the preserved region is pinned exactly
    np.testing.assert_array_equal(got[:, :7], orig[:, :7])
    assert not np.array_equal(got[:, 9:], orig[:, 9:])


def test_concat_conditioning_rejected_on_flow_models():
    b = pl.load_pipeline("tiny-flux", seed=0)
    cond = pl.encode_text_pooled(b, ["x"])
    cond.concat_latent = jnp.zeros((1, 8, 8, 5))
    neg = pl.encode_text_pooled(b, [""])
    with pytest.raises(ValueError, match="flow-family"):
        pl.img2img_latents(
            b, jnp.zeros((1, 8, 8, 16)), cond, neg, steps=1,
            sampler="euler", scheduler="normal", cfg_scale=1.0,
        )


def test_lora_loader_model_only(tmp_path, monkeypatch):
    """Model-only LoRA patches the UNet of a text-encoder-less bundle
    (UNETLoader product)."""
    from safetensors.numpy import save_file

    from comfyui_distributed_tpu.graph.nodes_core import LoraLoaderModelOnly
    from comfyui_distributed_tpu.models.io import flatten_params
    from comfyui_distributed_tpu.models.lora import lora_target_map
    from comfyui_distributed_tpu.models.registry import get_config
    import jax

    b = pl.load_unet("tiny-unet")
    targets = lora_target_map(get_config("tiny-unet"))
    # pick one targeted unet module and build a rank-2 LoRA for it
    name, (part, path) = next(
        (n, t) for n, t in targets.items() if t[0] == "unet"
    )
    flat = flatten_params(jax.device_get(b.params["unet"]))
    kernel = flat[path]
    rng = np.random.default_rng(5)
    down = rng.normal(0, 0.1, (2, kernel.shape[0])).astype(np.float32)
    up = rng.normal(0, 0.1, (kernel.shape[1], 2)).astype(np.float32)
    save_file(
        {
            f"{name}.lora_down.weight": down,
            f"{name}.lora_up.weight": up,
        },
        str(tmp_path / "test-lora.safetensors"),
    )
    monkeypatch.setenv("CDT_LORA_DIR", str(tmp_path))
    (patched,) = LoraLoaderModelOnly().load_lora_model_only(
        b, "test-lora", 1.0
    )
    new_flat = flatten_params(jax.device_get(patched.params["unet"]))
    assert not np.array_equal(new_flat[path], kernel)
    # original untouched
    np.testing.assert_array_equal(
        flatten_params(jax.device_get(b.params["unet"]))[path], kernel
    )
