"""Geometry/blend nodes: LatentFlip/Rotate/Crop/Blend, ImageFlip/
Rotate/Blend, EmptyImage, LoadImageMask."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_transform import (
    EmptyImage,
    ImageBlend,
    ImageFlip,
    ImageRotate,
    LatentBlend,
    LatentCrop,
    LatentFlip,
    LatentRotate,
    LoadImageMask,
)

pytestmark = pytest.mark.fast


def _latent(b=1, h=8, w=6, c=4):
    z = jnp.arange(b * h * w * c, dtype=jnp.float32).reshape(b, h, w, c)
    return {"samples": z}


def test_latent_flip_vertical_reverses_rows():
    lat = _latent()
    (out,) = LatentFlip().flip(lat, "x-axis: vertically")
    np.testing.assert_array_equal(
        np.asarray(out["samples"]), np.asarray(lat["samples"])[:, ::-1]
    )


def test_latent_flip_horizontal_reverses_cols_and_mask():
    lat = _latent()
    lat["noise_mask"] = jnp.arange(48, dtype=jnp.float32).reshape(1, 8, 6, 1)
    (out,) = LatentFlip().flip(lat, "y-axis: horizontally")
    np.testing.assert_array_equal(
        np.asarray(out["samples"]), np.asarray(lat["samples"])[:, :, ::-1]
    )
    np.testing.assert_array_equal(
        np.asarray(out["noise_mask"]),
        np.asarray(lat["noise_mask"])[:, :, ::-1],
    )


def test_latent_flip_rejects_unknown_method():
    with pytest.raises(ValueError):
        LatentFlip().flip(_latent(), "diagonal")


def test_latent_rotate_quarter_turns():
    lat = _latent()
    (out90,) = LatentRotate().rotate(lat, "90 degrees")
    # clockwise: the top row becomes the right column
    assert out90["samples"].shape == (1, 6, 8, 4)
    ref = np.rot90(np.asarray(lat["samples"]), k=-1, axes=(1, 2))
    np.testing.assert_array_equal(np.asarray(out90["samples"]), ref)
    (out360,) = LatentRotate().rotate(
        *LatentRotate().rotate(lat, "180 degrees"), "180 degrees"
    )
    np.testing.assert_array_equal(
        np.asarray(out360["samples"]), np.asarray(lat["samples"])
    )
    (outnone,) = LatentRotate().rotate(lat, "none")
    assert outnone["samples"] is lat["samples"]


def test_latent_crop_pixel_to_cell_conversion():
    lat = _latent(h=8, w=8)
    (out,) = LatentCrop().crop(lat, width=32, height=16, x=16, y=8)
    # 32/16/16/8 px -> 4/2/2/1 cells
    np.testing.assert_array_equal(
        np.asarray(out["samples"]),
        np.asarray(lat["samples"])[:, 1:3, 2:6, :],
    )


def test_latent_blend_lerps_and_validates():
    a, b = _latent(), _latent()
    b["samples"] = jnp.ones_like(b["samples"])
    (out,) = LatentBlend().blend(a, b, blend_factor=0.25)
    ref = np.asarray(a["samples"]) * 0.25 + np.asarray(b["samples"]) * 0.75
    np.testing.assert_allclose(np.asarray(out["samples"]), ref, rtol=1e-6)
    with pytest.raises(ValueError):
        LatentBlend().blend(a, _latent(h=4))


def test_image_flip_rotate():
    img = jnp.arange(2 * 4 * 6 * 3, dtype=jnp.float32).reshape(2, 4, 6, 3)
    (v,) = ImageFlip().flip(img, "x-axis: vertically")
    np.testing.assert_array_equal(np.asarray(v), np.asarray(img)[:, ::-1])
    (r,) = ImageRotate().rotate(img, "270 degrees")
    np.testing.assert_array_equal(
        np.asarray(r), np.rot90(np.asarray(img), k=-3, axes=(1, 2))
    )


@pytest.mark.parametrize(
    "mode,expect",
    [
        ("normal", 0.75),
        ("multiply", 0.5 * 0.75),
        ("screen", 1.0 - 0.5 * 0.25),
        ("overlay", 2.0 * 0.5 * 0.75),  # a == 0.5 takes the low branch
        ("difference", 0.25),
    ],
)
def test_image_blend_modes_full_factor(mode, expect):
    a = jnp.full((1, 2, 2, 3), 0.5)
    b = jnp.full((1, 2, 2, 3), 0.75)
    (out,) = ImageBlend().blend(a, b, blend_factor=1.0, blend_mode=mode)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_image_blend_soft_light_identity_at_half():
    # b == 0.5 leaves a unchanged in the W3C piecewise form
    a = jnp.asarray(np.linspace(0, 1, 12, dtype=np.float32)).reshape(
        1, 2, 2, 3
    )
    b = jnp.full((1, 2, 2, 3), 0.5)
    (out,) = ImageBlend().blend(a, b, blend_factor=1.0,
                                blend_mode="soft_light")
    np.testing.assert_allclose(np.asarray(out), np.asarray(a), atol=1e-6)


def test_image_blend_factor_zero_keeps_first():
    a = jnp.full((1, 2, 2, 3), 0.3)
    b = jnp.full((1, 2, 2, 3), 0.9)
    (out,) = ImageBlend().blend(a, b, blend_factor=0.0,
                                blend_mode="difference")
    np.testing.assert_allclose(np.asarray(out), 0.3, rtol=1e-6)


def test_image_blend_rejects_unknown_mode():
    a = jnp.zeros((1, 2, 2, 3))
    with pytest.raises(ValueError):
        ImageBlend().blend(a, a, blend_mode="dissolve")


def test_empty_image_color_unpack():
    (out,) = EmptyImage().generate(width=4, height=3, batch_size=2,
                                   color=0xFF8000)
    assert out.shape == (2, 3, 4, 3)
    np.testing.assert_allclose(
        np.asarray(out)[0, 0, 0], [1.0, 128 / 255.0, 0.0], rtol=1e-6
    )


def test_load_image_mask_channels(tmp_path):
    from PIL import Image

    arr = np.zeros((4, 4, 4), np.uint8)
    arr[..., 0] = 255  # red
    arr[..., 3] = 128  # alpha
    p = tmp_path / "m.png"
    Image.fromarray(arr, "RGBA").save(p)
    (red,) = LoadImageMask().load(str(p), "red")
    assert red.shape == (1, 4, 4)
    np.testing.assert_allclose(np.asarray(red), 1.0, rtol=1e-3)
    # alpha is inverted: transparent = 1 = regenerate
    (alpha,) = LoadImageMask().load(str(p), "alpha")
    np.testing.assert_allclose(
        np.asarray(alpha), 1.0 - 128 / 255.0, rtol=1e-2
    )
    with pytest.raises(ValueError):
        LoadImageMask().load(str(p), "luma")


def test_load_image_mask_no_alpha_and_missing_channel(tmp_path):
    from PIL import Image

    rgb = np.full((4, 4, 3), 200, np.uint8)
    p = tmp_path / "rgb.png"
    Image.fromarray(rgb, "RGB").save(p)
    (alpha,) = LoadImageMask().load(str(p), "alpha")
    np.testing.assert_allclose(np.asarray(alpha), 0.0)  # nothing to redo
    gray = np.full((4, 4), 100, np.uint8)
    pg = tmp_path / "l.png"
    Image.fromarray(gray, "L").save(pg)
    with pytest.raises(ValueError):
        LoadImageMask().load(str(pg), "green")


def test_load_image_alpha_inversion(tmp_path):
    from PIL import Image

    from comfyui_distributed_tpu.graph.nodes_core import LoadImage

    arr = np.zeros((4, 4, 4), np.uint8)
    arr[:, :2, 3] = 255  # left half opaque
    p = tmp_path / "rgba.png"
    Image.fromarray(arr, "RGBA").save(p)
    _img, mask = LoadImage().load(str(p))
    m = np.asarray(mask)[0]
    np.testing.assert_allclose(m[:, :2], 0.0)  # opaque -> keep
    np.testing.assert_allclose(m[:, 2:], 1.0)  # transparent -> regenerate
    # no alpha -> zeros
    rgb = tmp_path / "rgb2.png"
    Image.fromarray(np.zeros((4, 4, 3), np.uint8), "RGB").save(rgb)
    _img2, mask2 = LoadImage().load(str(rgb))
    np.testing.assert_allclose(np.asarray(mask2), 0.0)


def test_latent_batch_seed_behavior_flag():
    from comfyui_distributed_tpu.graph.nodes_transform import (
        LatentBatchSeedBehavior,
    )

    lat = _latent(b=3)
    (fixed,) = LatentBatchSeedBehavior().op(lat, "fixed")
    assert fixed["batch_index_fixed"] is True
    (rand,) = LatentBatchSeedBehavior().op(fixed, "random")
    assert "batch_index_fixed" not in rand
    with pytest.raises(ValueError):
        LatentBatchSeedBehavior().op(lat, "alternate")


@pytest.mark.slow
def test_fixed_batch_noise_makes_identical_batch_elements():
    import jax

    from comfyui_distributed_tpu.graph.nodes_core import KSampler
    from comfyui_distributed_tpu.graph.nodes_transform import (
        LatentBatchSeedBehavior,
    )
    from comfyui_distributed_tpu.models import pipeline as pl

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(17)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    pos = pl.encode_text(b, ["a tree"])
    neg = pl.encode_text(b, [""])
    lat = {"samples": jnp.zeros((3, 8, 8, 4))}
    (fixed_lat,) = LatentBatchSeedBehavior().op(lat, "fixed")
    (out_f,) = KSampler().sample(
        b, 5, 2, 4.0, "euler", "karras", pos, neg, fixed_lat
    )
    arr = np.asarray(out_f["samples"])
    np.testing.assert_array_equal(arr[0], arr[1])
    np.testing.assert_array_equal(arr[0], arr[2])
    # flag propagates through the output latent dict
    assert out_f.get("batch_index_fixed") is True
    # random: elements differ
    (out_r,) = KSampler().sample(
        b, 5, 2, 4.0, "euler", "karras", pos, neg, lat
    )
    ar = np.asarray(out_r["samples"])
    assert not np.allclose(ar[0], ar[1])
