"""SelfAttentionGuidance: attention capture, degraded-pass math, node
guards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import KSampler
from comfyui_distributed_tpu.graph.nodes_loaders import (
    SelfAttentionGuidance,
)
from comfyui_distributed_tpu.models import pipeline as pl

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(21)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


@pytest.mark.fast
def test_attention_capture_sows_probs():
    from comfyui_distributed_tpu.models.layers import AttentionBlock

    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 6, 8)).astype(np.float32)
    )
    blk = AttentionBlock(2, 4, jnp.float32, sow_attn=True)
    params = blk.init(jax.random.key(0), x)
    out, mut = blk.apply(params, x, mutable=["intermediates"])
    probs = jax.tree_util.tree_leaves(mut)[0]
    assert probs.shape == (2, 2, 6, 6)  # [B, heads, N, N]
    # rows are probability distributions
    np.testing.assert_allclose(
        np.asarray(probs.sum(axis=-1)), 1.0, atol=1e-5
    )
    # capture path numerics match the normal path
    normal = AttentionBlock(2, 4, jnp.float32).apply(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(normal), atol=1e-5
    )


def test_sag_capture_model_fn_contract(bundle):
    cap = pl._make_model_fn(bundle, bundle.params, sag_capture=True)
    neg = pl.encode_text(bundle, [""])
    x = jnp.asarray(
        np.random.default_rng(5).normal(size=(1, 8, 8, 4)).astype(np.float32)
    )
    sig = jnp.full((1,), 5.0)
    eps, probs, (mh, mw) = cap(x, sig, neg)
    assert eps.shape == x.shape
    assert probs.shape[0] == 1 and probs.ndim == 4
    assert probs.shape[2] == probs.shape[3] == mh * mw
    # and matches the normal model_fn's eps
    base = pl._make_model_fn(bundle, bundle.params)
    np.testing.assert_allclose(
        np.asarray(eps), np.asarray(base(x, sig, neg)), atol=2e-3
    )


def test_sag_capture_odd_latent_dims(bundle):
    """Downsample yields ceil(H/2) per level; the mid-grid derivation
    must match it for odd latent dims (a 520px image gives a 65-cell
    latent side)."""
    cap = pl._make_model_fn(bundle, bundle.params, sag_capture=True)
    neg = pl.encode_text(bundle, [""])
    x = jnp.asarray(
        np.random.default_rng(9).normal(size=(1, 9, 11, 4)).astype(
            np.float32
        )
    )
    sig = jnp.full((1,), 5.0)
    _eps, probs, (mh, mw) = cap(x, sig, neg)
    assert probs.shape[2] == probs.shape[3] == mh * mw
    # and the full guided path runs on the same odd shape
    pos = pl.encode_text(bundle, ["a castle"])
    (patched,) = SelfAttentionGuidance().patch(bundle, scale=0.7)
    g = pl.guided_model(patched, patched.params, 4.0)
    assert np.isfinite(np.asarray(g(x, sig, (pos, neg)))).all()


def test_sag_zero_scale_equals_plain_cfg(bundle):
    pos = pl.encode_text(bundle, ["a castle"])
    neg = pl.encode_text(bundle, [""])
    x = jnp.asarray(
        np.random.default_rng(3).normal(size=(1, 8, 8, 4)).astype(np.float32)
    )
    sig = jnp.full((1,), 5.0)
    g_plain = pl.guided_model(bundle, bundle.params, 4.0)
    (patched,) = SelfAttentionGuidance().patch(bundle, scale=0.0)
    g_sag = pl.guided_model(patched, patched.params, 4.0)
    np.testing.assert_allclose(
        np.asarray(g_sag(x, sig, (pos, neg))),
        np.asarray(g_plain(x, sig, (pos, neg))),
        atol=1e-4,
    )
    (p2,) = SelfAttentionGuidance().patch(bundle, scale=1.5)
    g2 = pl.guided_model(p2, p2.params, 4.0)
    assert not np.allclose(
        np.asarray(g2(x, sig, (pos, neg))),
        np.asarray(g_plain(x, sig, (pos, neg))),
        atol=1e-4,
    )


def test_sag_ksampler_end_to_end(bundle):
    (patched,) = SelfAttentionGuidance().patch(
        bundle, scale=0.8, blur_sigma=2.0
    )
    latent = {"samples": jnp.zeros((1, 8, 8, 4))}
    pos = pl.encode_text(bundle, ["a castle"])
    neg = pl.encode_text(bundle, [""])
    (out,) = KSampler().sample(
        patched, 3, 2, 4.0, "euler", "karras", pos, neg, latent
    )
    assert np.isfinite(np.asarray(out["samples"])).all()


@pytest.mark.fast
def test_sag_node_guards():
    b = object.__new__(pl.PipelineBundle)
    b.model_name = "tiny-flux"
    with pytest.raises(ValueError, match="family"):
        SelfAttentionGuidance().patch(b)
    b2 = object.__new__(pl.PipelineBundle)
    b2.model_name = "tiny-unet"
    b2.slg = None
    b2.cfg_rescale = None
    b2.dual_cfg = None
    b2.pag = pl.PAGSpec()
    b2.sag = None
    with pytest.raises(ValueError, match="PerturbedAttentionGuidance"):
        SelfAttentionGuidance().patch(b2)
