"""Conditioning combinator nodes (Combine / Average / ZeroOut /
SetTimestepRange / SetArea strength) and ControlNetApplyAdvanced —
the regional-prompting + scheduled-control surface, driven through
real KSampler runs on the tiny model."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_controlnet import (
    ConditioningAverage,
    ConditioningCombine,
    ConditioningSetArea,
    ConditioningSetTimestepRange,
    ConditioningZeroOut,
    ControlNetApply,
    ControlNetApplyAdvanced,
    ControlNetLoader,
)
from comfyui_distributed_tpu.graph.nodes_core import (
    EmptyLatentImage,
    KSampler,
)
from comfyui_distributed_tpu.models import pipeline as pl

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    import jax

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(123)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


def _run(bundle, pos, neg, seed=5, steps=2):
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    (out,) = KSampler().sample(
        bundle, seed, steps, 7.0, "euler", "karras", pos, neg, el
    )
    return np.asarray(out["samples"])


def test_combine_produces_entry_list(bundle):
    a = pl.encode_text_pooled(bundle, ["forest"])
    b = pl.encode_text_pooled(bundle, ["city"])
    (combined,) = ConditioningCombine().combine(a, b)
    assert isinstance(combined, list) and len(combined) == 2
    # nested combine flattens
    (three,) = ConditioningCombine().combine(combined, a)
    assert len(three) == 3


def test_regional_areas_change_output(bundle):
    a = pl.encode_text_pooled(bundle, ["forest"])
    b = pl.encode_text_pooled(bundle, ["city"])
    neg = pl.encode_text_pooled(bundle, [""])
    (left,) = ConditioningSetArea().set_area(a, 16, 32, 0, 0, 1.0)
    (right,) = ConditioningSetArea().set_area(b, 16, 32, 16, 0, 1.0)
    (combined,) = ConditioningCombine().combine(left, right)
    regional = _run(bundle, combined, neg)
    plain = _run(bundle, a, neg)
    assert regional.shape == plain.shape
    assert not np.allclose(regional, plain)


def test_full_window_timestep_range_matches_plain(bundle):
    """A [0, 1] window is always active: composed through a single
    always-on entry, the prediction equals the direct model eval.
    Compared at the single-eval level with identical program structure
    — the bf16 compute dtype makes cross-structure trajectory
    comparisons rounding-noisy."""
    from comfyui_distributed_tpu.ops import samplers as smp

    neg = pl.encode_text_pooled(bundle, ["ugly"])
    (ranged,) = ConditioningSetTimestepRange().set_range(neg, 0.0, 1.0)
    base_fn = pl._make_model_fn(bundle, bundle.params)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(1, 16, 16, 4)), jnp.float32)
    for sigma in (10.0, 0.05):
        sig = jnp.asarray([sigma])
        direct = np.asarray(base_fn(x, sig, neg))
        composed = np.asarray(
            smp.composite_eps(
                base_fn, x, sig, ranged, pl.percent_converter(bundle)
            )
        )
        np.testing.assert_allclose(composed, direct, atol=1e-6)


def test_timestep_split_negative_differs(bundle):
    """The SD3 negative recipe: real negative early, zeroed negative
    late — must differ from the plain negative run."""
    pos = pl.encode_text_pooled(bundle, ["forest"])
    neg = pl.encode_text_pooled(bundle, ["ugly"])
    (zeroed,) = ConditioningZeroOut().zero_out(neg)
    (early,) = ConditioningSetTimestepRange().set_range(neg, 0.0, 0.3)
    (late,) = ConditioningSetTimestepRange().set_range(zeroed, 0.3, 1.0)
    (split,) = ConditioningCombine().combine(early, late)
    assert not np.allclose(_run(bundle, pos, split), _run(bundle, pos, neg))


def test_zero_out_zeros_payloads(bundle):
    cond = pl.encode_text_pooled(bundle, ["x"])
    (z,) = ConditioningZeroOut().zero_out(cond)
    assert not np.any(np.asarray(z.context))
    assert not np.any(np.asarray(z.pooled))


def test_average_lerps(bundle):
    a = pl.encode_text_pooled(bundle, ["forest"])
    b = pl.encode_text_pooled(bundle, ["city"])
    (half,) = ConditioningAverage().average(a, b, 0.5)
    np.testing.assert_allclose(
        np.asarray(half.context),
        0.5 * np.asarray(a.context) + 0.5 * np.asarray(b.context),
        atol=1e-6,
    )
    (all_a,) = ConditioningAverage().average(a, b, 1.0)
    np.testing.assert_allclose(
        np.asarray(all_a.context), np.asarray(a.context), atol=1e-6
    )


def test_average_conforms_from_to_to_shape(bundle):
    """`from` truncates to `to`'s token length (reference behavior):
    the output always keeps conditioning_to's shape."""
    from comfyui_distributed_tpu.graph.nodes_core import ConditioningConcat

    a = pl.encode_text_pooled(bundle, ["short"])
    b = pl.encode_text_pooled(bundle, ["other"])
    (long_b,) = ConditioningConcat().concat(b, b)  # 2x token length
    (out,) = ConditioningAverage().average(a, long_b, 0.5)
    assert out.context.shape == a.context.shape
    t = a.context.shape[1]
    np.testing.assert_allclose(
        np.asarray(out.context),
        0.5 * np.asarray(a.context) + 0.5 * np.asarray(long_b.context)[:, :t],
        atol=1e-6,
    )
    # and padding when `from` is shorter
    (out2,) = ConditioningAverage().average(long_b, a, 0.5)
    assert out2.context.shape == long_b.context.shape


def test_controlnet_advanced_applies_to_both_sides(bundle):
    (cn,) = ControlNetLoader().load(
        "tile", model=bundle, context=type("C", (), {"pipelines": {}})()
    )
    pos = pl.encode_text_pooled(bundle, ["forest"])
    neg = pl.encode_text_pooled(bundle, [""])
    hint = jnp.ones((1, 32, 32, 3)) * 0.5
    p2, n2 = ControlNetApplyAdvanced().apply(pos, neg, cn, hint, 0.8, 0.0, 1.0)
    assert p2.control_hint is not None and n2.control_hint is not None
    assert p2.control_range == (0.0, 1.0)
    # strength 0 short-circuits to passthrough
    p3, n3 = ControlNetApplyAdvanced().apply(pos, neg, cn, hint, 0.0)
    assert p3 is pos and n3 is neg


def test_controlnet_window_gates_model_evals(bundle):
    """The [start, end) window gates the hint per model eval: inside
    the window the prediction matches a full-window hint, outside it
    matches a closed-window (never-active) hint. Comparisons are
    between IDENTICALLY-structured programs — the bf16 compute dtype
    makes cross-structure (batched-CFG vs two-pass) comparisons noisy
    by amplified rounding, and the "tile" ControlNet's output conv is
    zero-init, so the fixture perturbs it to make the hint real."""
    import dataclasses
    import jax

    ctx = type("C", (), {"pipelines": {}})()
    (cn,) = ControlNetLoader().load("tile", model=bundle, context=ctx)
    rng = np.random.default_rng(7)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    cn = dataclasses.replace(
        cn, params=jax.tree_util.tree_map(fix, cn.params)
    )
    pos = pl.encode_text_pooled(bundle, ["forest"])
    neg = pl.encode_text_pooled(bundle, [""])
    hint = jnp.ones((1, 32, 32, 3)) * 0.5
    m = pl.guided_model(bundle, bundle.params, 7.0)
    rng2 = np.random.default_rng(0)
    x = jnp.asarray(rng2.normal(size=(1, 16, 16, 4)), jnp.float32)

    def eps_at(sigma, start, end):
        p, n = ControlNetApplyAdvanced().apply(
            pos, neg, cn, hint, 1.0, start, end
        )
        return np.asarray(m(x, jnp.asarray([sigma]), (p, n)))

    hi, lo = 10.0, 0.05  # early vs late sampling sigmas
    full_hi, full_lo = eps_at(hi, 0.0, 1.0), eps_at(lo, 0.0, 1.0)
    off_hi, off_lo = eps_at(hi, 0.5, 0.5), eps_at(lo, 0.5, 0.5)
    # the hint genuinely changes predictions
    assert not np.allclose(full_hi, off_hi)
    # first-half window: active early (== full), inactive late (== off)
    early_hi, early_lo = eps_at(hi, 0.0, 0.5), eps_at(lo, 0.0, 0.5)
    np.testing.assert_allclose(early_hi, full_hi, atol=1e-6)
    np.testing.assert_allclose(early_lo, off_lo, atol=1e-6)
    # and the closed window differs from full at low sigma too (the
    # full window is still applying the hint there)
    assert not np.allclose(off_lo, full_lo)


def test_usdu_rejects_area_conditioning(bundle):
    from comfyui_distributed_tpu.ops import tiles as tile_ops
    from comfyui_distributed_tpu.ops import upscale as up

    pos = pl.encode_text_pooled(bundle, ["x"])
    (area,) = ConditioningSetArea().set_area(pos, 16, 16, 0, 0, 1.0)
    grid = tile_ops.calculate_tiles(64, 64, 32, 4)
    with pytest.raises(ValueError, match="area-restricted"):
        up.prep_cond_for_tiles(area, grid)
