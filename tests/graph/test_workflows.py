"""Every bundled workflow must validate against the node registry.

The reference ships example graphs (reference workflows/*.json) that
its CI keeps loadable implicitly through ComfyUI; here the drift guard
is explicit — class names, required inputs, link arity, acyclicity,
and model names are all checked without executing anything."""

import glob
import json
import os

import pytest

from comfyui_distributed_tpu.graph.executor import validate_prompt
from comfyui_distributed_tpu.models.registry import MODEL_REGISTRY

pytestmark = pytest.mark.fast

WORKFLOW_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "workflows",
)
WORKFLOWS = sorted(glob.glob(os.path.join(WORKFLOW_DIR, "*.json")))


def test_workflows_present():
    assert len(WORKFLOWS) >= 6


@pytest.mark.parametrize(
    "path", WORKFLOWS, ids=[os.path.basename(p) for p in WORKFLOWS]
)
def test_bundled_workflow_validates(path):
    with open(path) as fh:
        prompt = json.load(fh)
    validate_prompt(prompt)  # raises on any structural problem
    for node in prompt.values():
        name = (node.get("inputs") or {}).get("ckpt_name")
        if name is not None:
            assert name in MODEL_REGISTRY, f"unknown model {name!r} in {path}"
