"""PerpNegGuider + smp.perp_neg_model math, and SaveAnimatedPNG/WEBP."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.ops import samplers as smp


@pytest.mark.fast
def test_perp_neg_math_orthogonal_negative_pushes():
    """With eps(pos), eps(neg), eps(empty) crafted so the relative
    negative is exactly orthogonal to the relative positive, the
    projection removes nothing: out = empty + cfg*(pos - s*neg)."""
    vals = {}

    def model_fn(x, sigma, cond):
        return cond

    x = jnp.zeros((1, 1, 1, 2))
    sig = jnp.ones((1,))
    e_empty = jnp.zeros_like(x)
    e_pos = jnp.asarray([1.0, 0.0]).reshape(1, 1, 1, 2)
    e_neg = jnp.asarray([0.0, 2.0]).reshape(1, 1, 1, 2)  # orthogonal
    g = smp.perp_neg_model(model_fn, 3.0, 0.5)
    out = g(x, sig, ((e_pos, e_neg), e_empty))
    expect = 0.0 + 3.0 * (np.asarray([1.0, 0.0]) - 0.5 * np.asarray([0.0, 2.0]))
    np.testing.assert_allclose(
        np.asarray(out).reshape(2), expect, rtol=1e-6
    )


@pytest.mark.fast
def test_perp_neg_aligned_negative_is_removed():
    """A negative PARALLEL to the positive must vanish entirely (the
    node's whole point): out reduces to plain CFG on the positive."""

    def model_fn(x, sigma, cond):
        return cond

    x = jnp.zeros((1, 1, 1, 2))
    sig = jnp.ones((1,))
    e_empty = jnp.zeros_like(x)
    e_pos = jnp.asarray([1.0, 1.0]).reshape(1, 1, 1, 2)
    e_neg = 0.7 * e_pos  # perfectly aligned
    g = smp.perp_neg_model(model_fn, 4.0, 2.0)
    out = g(x, sig, ((e_pos, e_neg), e_empty))
    np.testing.assert_allclose(
        np.asarray(out), 4.0 * np.asarray(e_pos), rtol=1e-5
    )


@pytest.mark.slow
def test_perp_neg_guider_end_to_end():
    import jax

    from comfyui_distributed_tpu.graph.nodes_custom_sampling import (
        PerpNegGuider,
        RandomNoise,
        SamplerCustomAdvanced,
        SamplerSpec,
    )
    from comfyui_distributed_tpu.models import pipeline as pl

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(31)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    pos = pl.encode_text(b, ["a castle"])
    neg = pl.encode_text(b, ["blurry"])
    empty = pl.encode_text(b, [""])
    sig = smp.get_sigmas("karras", 3)
    latent = {"samples": jnp.zeros((1, 8, 8, 4))}
    (noise,) = RandomNoise().get_noise(5)
    (g,) = PerpNegGuider().get_guider(
        b, pos, neg, empty, cfg=4.0, neg_scale=1.0
    )
    out, _ = SamplerCustomAdvanced().sample(
        noise, g, SamplerSpec("euler"), sig, latent
    )
    assert np.isfinite(np.asarray(out["samples"])).all()


@pytest.mark.fast
def test_save_animated_png_webp(tmp_path, monkeypatch):
    from PIL import Image

    from comfyui_distributed_tpu.graph.nodes_video import (
        SaveAnimatedPNG,
        SaveAnimatedWEBP,
    )

    monkeypatch.setenv("CDT_OUTPUT_DIR", str(tmp_path))

    class _Ctx:
        config = {}

    frames = jnp.stack(
        [jnp.full((8, 8, 3), v) for v in (0.0, 0.5, 1.0)]
    )
    SaveAnimatedPNG().save(frames, "anim", fps=4, context=_Ctx())
    SaveAnimatedWEBP().save(frames, "anim", fps=4, context=_Ctx())
    png = tmp_path / "anim_00000.png"
    webp = tmp_path / "anim_00000.webp"
    assert png.exists() and webp.exists()
    im = Image.open(webp)
    assert getattr(im, "n_frames", 1) == 3
    # counter scan: second save does not clobber
    SaveAnimatedPNG().save(frames, "anim", fps=4, context=_Ctx())
    assert (tmp_path / "anim_00001.png").exists()
    # max-counter semantics: a numbering GAP must not cause a clobber
    (tmp_path / "anim_00001.png").unlink()
    (tmp_path / "anim_00005.png").write_bytes(b"sentinel")
    SaveAnimatedPNG().save(frames, "anim", fps=4, context=_Ctx())
    assert (tmp_path / "anim_00005.png").read_bytes() == b"sentinel"
    assert (tmp_path / "anim_00006.png").exists()
    # prefix filter: 'anim' does not count 'animated' files
    (tmp_path / "animated_00099.webp").write_bytes(b"x")
    SaveAnimatedWEBP().save(frames, "anim", fps=4, context=_Ctx())
    assert (tmp_path / "anim_00001.webp").exists()
