"""Latent math, ImageQuantize, ModelMerge/CLIPMerge, and
CLIPTextEncodeFlux."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_loaders import (
    CLIPMergeSimple,
    ModelMergeSimple,
)
from comfyui_distributed_tpu.graph.nodes_transform import (
    ImageQuantize,
    LatentAdd,
    LatentInterpolate,
    LatentMultiply,
    LatentSubtract,
)
from comfyui_distributed_tpu.models import pipeline as pl


def _lat(val, shape=(1, 4, 4, 4)):
    return {"samples": jnp.full(shape, float(val))}


@pytest.mark.fast
def test_latent_add_subtract_multiply():
    (s,) = LatentAdd().op(_lat(2.0), _lat(3.0))
    np.testing.assert_allclose(np.asarray(s["samples"]), 5.0)
    (d,) = LatentSubtract().op(_lat(2.0), _lat(3.0))
    np.testing.assert_allclose(np.asarray(d["samples"]), -1.0)
    (m,) = LatentMultiply().op(_lat(2.0), multiplier=1.5)
    np.testing.assert_allclose(np.asarray(m["samples"]), 3.0)
    with pytest.raises(ValueError):
        LatentAdd().op(_lat(1.0), _lat(1.0, shape=(1, 2, 2, 4)))


@pytest.mark.fast
def test_latent_interpolate_preserves_magnitude():
    rng = np.random.default_rng(0)
    a = {"samples": jnp.asarray(rng.normal(size=(2, 4, 4, 4)).astype(np.float32))}
    b = {"samples": jnp.asarray(rng.normal(size=(2, 4, 4, 4)).astype(np.float32))}
    (out,) = LatentInterpolate().op(a, b, ratio=0.5)
    axes = (1, 2, 3)
    na = np.linalg.norm(np.asarray(a["samples"]).reshape(2, -1), axis=1)
    nb = np.linalg.norm(np.asarray(b["samples"]).reshape(2, -1), axis=1)
    no = np.linalg.norm(np.asarray(out["samples"]).reshape(2, -1), axis=1)
    np.testing.assert_allclose(no, 0.5 * na + 0.5 * nb, rtol=1e-5)
    # endpoints are exact
    (e1,) = LatentInterpolate().op(a, b, ratio=1.0)
    np.testing.assert_allclose(
        np.asarray(e1["samples"]), np.asarray(a["samples"]), rtol=1e-5
    )


@pytest.mark.fast
def test_image_quantize():
    img = jnp.asarray(np.linspace(0, 1, 12, dtype=np.float32)).reshape(
        1, 2, 2, 3
    )
    (out,) = ImageQuantize().quantize(img, colors=2)
    assert set(np.unique(np.asarray(out))) <= {0.0, 1.0}
    (out8,) = ImageQuantize().quantize(img, colors=9)
    np.testing.assert_allclose(
        np.asarray(out8), np.round(np.asarray(img) * 8) / 8, atol=1e-6
    )
    with pytest.raises(ValueError):
        ImageQuantize().quantize(img, dither="floyd")
    with pytest.raises(ValueError):
        ImageQuantize().quantize(img, colors=1)


@pytest.mark.slow
def test_model_and_clip_merge():
    b1 = pl.load_pipeline("tiny-unet", seed=0)
    b2 = pl.load_pipeline("tiny-unet", seed=7)
    (merged,) = ModelMergeSimple().merge(b1, b2, ratio=0.25)
    l1 = jax.tree_util.tree_leaves(b1.params["unet"])
    l2 = jax.tree_util.tree_leaves(b2.params["unet"])
    lm = jax.tree_util.tree_leaves(merged.params["unet"])
    for a, b, m in zip(l1, l2, lm):
        if jnp.issubdtype(a.dtype, jnp.floating):
            np.testing.assert_allclose(
                np.asarray(m),
                np.asarray(a) * 0.25 + np.asarray(b) * 0.75,
                atol=1e-5,
            )
    # non-unet params stay model1's
    assert merged.params["vae"] is b1.params["vae"]
    (cm,) = CLIPMergeSimple().merge(b1, b2, ratio=0.5)
    t1 = jax.tree_util.tree_leaves(b1.params["te"])[0]
    t2 = jax.tree_util.tree_leaves(b2.params["te"])[0]
    tm = jax.tree_util.tree_leaves(cm.params["te"])[0]
    np.testing.assert_allclose(
        np.asarray(tm), (np.asarray(t1) + np.asarray(t2)) / 2.0, atol=1e-5
    )
    # architecture mismatch is loud
    b3 = pl.load_pipeline("tiny-sd3", seed=0)
    with pytest.raises(ValueError):
        ModelMergeSimple().merge(b1, b3)


@pytest.mark.slow
def test_clip_text_encode_flux_node():
    from comfyui_distributed_tpu.graph.nodes_core import CLIPTextEncodeFlux

    b = pl.load_pipeline("tiny-flux", seed=0)
    (cond,) = CLIPTextEncodeFlux().encode(
        b, clip_l="a cat", t5xxl="a detailed cat", guidance=4.5
    )
    assert cond.guidance == 4.5
    assert cond.context.ndim == 3 and cond.pooled.ndim == 2
    # identical prompts + no guidance reduce to encode_text_pooled
    (same,) = CLIPTextEncodeFlux().encode(
        b, clip_l="a cat", t5xxl="a cat", guidance=3.5
    )
    ref = pl.encode_text_pooled(b, ["a cat"])
    np.testing.assert_allclose(
        np.asarray(same.context), np.asarray(ref.context), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(same.pooled), np.asarray(ref.pooled), atol=1e-5
    )
    # family guard
    b2 = object.__new__(pl.PipelineBundle)
    b2.model_name = "tiny-unet"
    with pytest.raises(ValueError, match="mmdit"):
        CLIPTextEncodeFlux().encode(b2, clip_l="x", t5xxl="y")
