"""Dynamic (image-queue) USDU mode: worker pulls whole frames; master
assembles the batch in order; dead workers' frames recovered."""

import threading
import types

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph import ExecutionContext
from comfyui_distributed_tpu.graph.usdu_elastic import (
    run_master_dynamic,
    run_worker_dynamic,
)
from comfyui_distributed_tpu.jobs import JobStore
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.utils.async_helpers import run_async_in_server_loop


@pytest.fixture(scope="module")
def bundle():
    return pl.load_pipeline("tiny-unet", seed=0)


class ScriptedDynamicClient:
    def __init__(self, image_ids):
        self.image_ids = list(image_ids)
        self.frames = {}

    def poll_ready(self):
        return True

    def request_tile(self):
        if not self.image_ids:
            return None
        idx = self.image_ids.pop(0)
        return {"image_idx": idx, "estimated_remaining": len(self.image_ids)}

    def submit_image(self, image_idx, data_url, is_last):
        self.frames[image_idx] = (data_url, is_last)

    def heartbeat(self):
        pass


def test_worker_dynamic_processes_whole_frames(bundle):
    img = jnp.asarray(np.random.default_rng(0).random((3, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    client = ScriptedDynamicClient([1, 2])
    run_worker_dynamic(
        bundle, img, pos, neg, job_id="dj", worker_id="w1", master_url="",
        upscale_by=2.0, tile=64, padding=16, steps=1, sampler="euler",
        scheduler="karras", cfg=1.0, denoise=0.3, seed=3, client=client,
    )
    assert set(client.frames) == {1, 2}
    assert client.frames[2][1] is True  # last pull flagged is_last
    from comfyui_distributed_tpu.utils.image import decode_image_data_url

    frame = decode_image_data_url(client.frames[1][0])
    assert frame.shape == (128, 128, 3)


def test_master_dynamic_assembles_ordered_batch(bundle, server_loop):
    img = jnp.asarray(np.random.default_rng(1).random((3, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    store = JobStore()
    ctx = ExecutionContext(
        server=types.SimpleNamespace(job_store=store), config={"workers": []}
    )
    out = run_master_dynamic(
        bundle, img, pos, neg, job_id="dj2", enabled_worker_ids=[],
        upscale_by=2.0, tile=64, padding=16, steps=1, sampler="euler",
        scheduler="karras", cfg=1.0, denoise=0.3, seed=5, context=ctx,
    )
    assert out.shape == (3, 128, 128, 3)
    assert np.isfinite(np.asarray(out)).all()
    # frames must differ (different content + folded frame keys)
    arr = np.asarray(out)
    assert arr[0].tobytes() != arr[1].tobytes()


def test_node_mode_selection(bundle):
    from comfyui_distributed_tpu.graph.nodes_upscale import (
        UltimateSDUpscaleDistributed,
    )

    node = UltimateSDUpscaleDistributed()
    img = jnp.asarray(np.random.default_rng(2).random((1, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    # no workers, no mesh → local path executes fine end-to-end
    (out,) = node.run(
        image=img, model=bundle, positive=pos, negative=neg, vae=bundle,
        seed=1, steps=1, cfg=1.0, sampler_name="euler", scheduler="karras",
        denoise=0.3, upscale_by=2.0, tile_width=64, tile_height=64,
        tile_padding=16, context=ExecutionContext(),
    )
    assert out.shape == (1, 128, 128, 3)


def test_node_with_upscale_model(bundle):
    from comfyui_distributed_tpu.graph.nodes_upscale import (
        UltimateSDUpscaleDistributed,
    )
    from comfyui_distributed_tpu.models.upscaler import load_upscale_model

    node = UltimateSDUpscaleDistributed()
    img = jnp.asarray(np.random.default_rng(3).random((1, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    (out,) = node.run(
        image=img, model=bundle, positive=pos, negative=neg, vae=bundle,
        seed=1, steps=1, cfg=1.0, sampler_name="euler", scheduler="karras",
        denoise=0.3, upscale_by=2.0, tile_width=64, tile_height=64,
        tile_padding=16, upscale_model=load_upscale_model("2x-test"),
        context=ExecutionContext(),
    )
    assert out.shape == (1, 128, 128, 3)
