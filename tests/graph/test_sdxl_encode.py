"""CLIPTextEncodeSDXL: per-tower prompts + adm size-conditioning
override (the SDXL workflow surface the reference inherits from
ComfyUI)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    CLIPTextEncodeSDXL,
    EmptyLatentImage,
    KSampler,
)
from comfyui_distributed_tpu.models import pipeline as pl

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    import jax

    b = pl.load_pipeline("tiny-unet-adm", seed=0)
    rng = np.random.default_rng(123)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


def test_same_prompts_reduce_to_plain_encode(bundle):
    """With text_g == text_l the dual-tower encode equals
    encode_text_pooled on the same bundle (same towers, same texts)."""
    (cond,) = CLIPTextEncodeSDXL().encode(
        bundle, 1024, 1024, 0, 0, 1024, 1024, "a cat", "a cat"
    )
    plain = pl.encode_text_pooled(bundle, ["a cat"])
    np.testing.assert_allclose(
        np.asarray(cond.context), np.asarray(plain.context), atol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(cond.pooled), np.asarray(plain.pooled), atol=1e-6
    )
    assert cond.size_cond == (1024, 1024, 0, 0, 1024, 1024)


def test_per_tower_prompts_differ(bundle):
    (ab,) = CLIPTextEncodeSDXL().encode(
        bundle, 1024, 1024, 0, 0, 1024, 1024, "a", "b"
    )
    (aa,) = CLIPTextEncodeSDXL().encode(
        bundle, 1024, 1024, 0, 0, 1024, 1024, "a", "a"
    )
    assert not np.allclose(np.asarray(ab.context), np.asarray(aa.context))


def test_requires_dual_tower():
    single = pl.load_clip(["tiny-te"], layout="sd")
    with pytest.raises(ValueError, match="dual-tower"):
        CLIPTextEncodeSDXL().encode(single, 1024, 1024, 0, 0, 1024, 1024,
                                    "x", "x")


def test_size_cond_feeds_the_adm_vector(bundle):
    """KSampler output changes with the size ints, and explicitly
    passing the default (latent sizes, zero crops) reproduces the
    no-override output exactly."""
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    neg = pl.encode_text_pooled(bundle, [""])

    def run(cond):
        (out,) = KSampler().sample(
            bundle, 5, 2, 7.0, "euler", "karras", cond, neg, el, denoise=1.0
        )
        return np.asarray(out["samples"])

    plain = pl.encode_text_pooled(bundle, ["a cat"])
    base = run(plain)
    (explicit_default,) = CLIPTextEncodeSDXL().encode(
        bundle, 32, 32, 0, 0, 32, 32, "a cat", "a cat"
    )
    np.testing.assert_allclose(run(explicit_default), base, atol=1e-6)
    (cropped,) = CLIPTextEncodeSDXL().encode(
        bundle, 64, 64, 16, 16, 32, 32, "a cat", "a cat"
    )
    assert not np.allclose(run(cropped), base)
