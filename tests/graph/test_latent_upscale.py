"""Hi-res-fix substrate: LatentUpscale / LatentUpscaleBy (ComfyUI
parity nodes the reference's users chain between two KSamplers)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    KSampler,
    LatentUpscale,
    LatentUpscaleBy,
)
from comfyui_distributed_tpu.models import pipeline as pl

pytestmark = pytest.mark.slow


def test_latent_upscale_shapes_and_mask():
    z = jnp.zeros((1, 8, 8, 4))
    mask = jnp.ones((1, 8, 8, 1))
    (out,) = LatentUpscale().upscale(
        {"samples": z, "noise_mask": mask}, "nearest-exact", 128, 128
    )
    assert out["samples"].shape == (1, 16, 16, 4)
    assert out["noise_mask"].shape == (1, 16, 16, 1)
    assert out["width"] == 128 and out["height"] == 128


def test_latent_upscale_center_crop():
    """crop='center' trims the source to the target aspect around the
    center before resizing (common_upscale parity)."""
    cols = jnp.broadcast_to(
        jnp.arange(16.0)[None, None, :, None], (1, 8, 16, 4)
    )
    (out,) = LatentUpscale().upscale(
        {"samples": cols}, "nearest-exact", 64, 64, crop="center"
    )
    got = np.asarray(out["samples"])
    assert got.shape == (1, 8, 8, 4)
    # columns come from the CENTER window (4..11), not a squeeze of 0..15
    assert got.min() >= 4.0 and got.max() <= 11.0
    with pytest.raises(ValueError, match="crop"):
        LatentUpscale().upscale(
            {"samples": cols}, "nearest-exact", 64, 64, crop="sideways"
        )


def test_latent_upscale_zero_dim_preserves_aspect():
    """ComfyUI convention: width/height 0 = keep aspect; 0/0 = noop."""
    z = jnp.zeros((1, 12, 8, 4))  # 96x64 px at the 8x convention
    (out,) = LatentUpscale().upscale({"samples": z}, "bilinear", 0, 192)
    assert out["samples"].shape == (1, 24, 16, 4)
    (noop,) = LatentUpscale().upscale({"samples": z}, "bilinear", 0, 0)
    assert noop["samples"].shape == (1, 12, 8, 4)
    with pytest.raises(ValueError, match="upscale_method"):
        LatentUpscale().upscale({"samples": z}, "nearset-exact", 64, 64)


def test_latent_upscale_by_factor():
    z = jnp.linspace(0, 1, 8 * 8 * 4).reshape(1, 8, 8, 4)
    (out,) = LatentUpscaleBy().upscale({"samples": z}, "bilinear", 1.5)
    assert out["samples"].shape == (1, 12, 12, 4)
    assert np.isfinite(np.asarray(out["samples"])).all()


def test_image_scale_aspect_and_crop():
    """ImageScale follows the same conventions: 0-dim keeps aspect,
    center crop trims to the target aspect, bad methods raise."""
    from comfyui_distributed_tpu.graph.nodes_core import ImageScale

    img = jnp.broadcast_to(
        jnp.arange(16.0)[None, None, :, None] / 15.0, (1, 8, 16, 3)
    )
    (out,) = ImageScale().scale(img, "nearest", 0, 64)
    assert out.shape == (1, 64, 128, 3)
    (c,) = ImageScale().scale(img, "nearest", 64, 64, crop="center")
    assert c.shape == (1, 64, 64, 3)
    arr = np.asarray(c)
    assert arr.min() >= 4.0 / 15.0 - 1e-6
    assert arr.max() <= 11.0 / 15.0 + 1e-6
    with pytest.raises(ValueError, match="upscale_method"):
        ImageScale().scale(img, "nearset", 64, 64)


def test_hires_fix_chain():
    """txt2img pass -> latent upscale -> refine pass, the canonical
    hi-res-fix graph."""
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    pos = pl.encode_text_pooled(bundle, ["p"])
    neg = pl.encode_text_pooled(bundle, [""])
    base = {"samples": jnp.zeros((1, 4, 4, 4)), "width": 32, "height": 32}
    (first,) = KSampler().sample(
        bundle, 3, 2, 1.0, "euler", "karras", pos, neg, base, denoise=1.0
    )
    (up,) = LatentUpscaleBy().upscale(first, "nearest-exact", 2.0)
    assert up["samples"].shape == (1, 8, 8, 4)
    (second,) = KSampler().sample(
        bundle, 4, 2, 1.0, "euler", "karras", pos, neg, up, denoise=0.5
    )
    arr = np.asarray(second["samples"])
    assert arr.shape == (1, 8, 8, 4)
    assert np.isfinite(arr).all()
