"""Prompt indexing, pruning, delegate prep, job ids, overrides —
covering the scenarios of reference tests/test_prompt_transform.py
against our re-designed implementation."""

import copy

from comfyui_distributed_tpu.graph import prompt as pt


def _workflow():
    """txt2img + collector + save, with a side branch only the master needs."""
    return {
        "1": {"class_type": "CheckpointLoaderSimple", "inputs": {"ckpt_name": "tiny-unet"}},
        "2": {"class_type": "CLIPTextEncode", "inputs": {"text": "cat", "clip": ["1", 1]}},
        "3": {"class_type": "CLIPTextEncode", "inputs": {"text": "", "clip": ["1", 1]}},
        "4": {"class_type": "EmptyLatentImage", "inputs": {"width": 64, "height": 64, "batch_size": 1}},
        "5": {"class_type": "DistributedSeed", "inputs": {"seed": 42}},
        "6": {
            "class_type": "KSampler",
            "inputs": {
                "model": ["1", 0], "seed": ["5", 0], "steps": 2, "cfg": 5.0,
                "sampler_name": "euler", "scheduler": "karras",
                "positive": ["2", 0], "negative": ["3", 0],
                "latent_image": ["4", 0], "denoise": 1.0,
            },
        },
        "7": {"class_type": "VAEDecode", "inputs": {"samples": ["6", 0], "vae": ["1", 2]}},
        "8": {"class_type": "DistributedCollector", "inputs": {"images": ["7", 0]}},
        "9": {"class_type": "SaveImage", "inputs": {"images": ["8", 0], "filename_prefix": "out"}},
    }


def test_index_lookup_and_closures():
    p = _workflow()
    idx = pt.PromptIndex(p)
    assert idx.nodes_of_class("DistributedCollector") == ["8"]
    assert idx.has_distributed_nodes()
    up = idx.upstream_closure("8")
    assert up == frozenset({"1", "2", "3", "4", "5", "6", "7", "8"})
    down = idx.downstream_closure("8")
    assert down == frozenset({"8", "9"})


def test_prune_for_worker_drops_downstream_adds_sink():
    p = _workflow()
    pruned = pt.prune_prompt_for_worker(p)
    assert "9" not in pruned  # SaveImage is master-only
    assert "8" in pruned and "1" in pruned
    sinks = [n for n in pruned.values() if n["class_type"] == "PreviewImage"]
    assert len(sinks) == 1
    assert sinks[0]["inputs"]["images"] == ["8", 0]
    # original untouched
    assert "9" in p


def test_prune_without_distributed_nodes_is_identity():
    p = {"1": {"class_type": "EmptyLatentImage", "inputs": {}}}
    assert pt.prune_prompt_for_worker(p) == p


def test_delegate_master_keeps_downstream_with_placeholder():
    p = _workflow()
    delegate = pt.prepare_delegate_master_prompt(p)
    assert set(delegate) >= {"8", "9"}
    assert "6" not in delegate  # no sampling on a delegate master
    placeholders = [
        nid for nid, n in delegate.items()
        if n["class_type"] == "DistributedEmptyImage"
    ]
    assert len(placeholders) == 1
    assert delegate["8"]["inputs"]["images"] == [placeholders[0], 0]


def test_job_id_map_unique_per_node():
    p = _workflow()
    p["10"] = {"class_type": "UltimateSDUpscaleDistributed", "inputs": {}}
    ids = pt.generate_job_id_map(p)
    assert set(ids) == {"8", "10"}
    assert ids["8"] != ids["10"]
    assert ids["8"].endswith("_8")


def test_overrides_master_vs_worker():
    p = _workflow()
    master = pt.apply_participant_overrides(
        p, pt.ParticipantInfo(is_worker=False, job_ids={"8": "jobA"},
                              enabled_worker_ids=["w1", "w2"]),
    )
    assert master["5"]["inputs"]["is_worker"] is False
    assert master["8"]["inputs"]["job_id"] == "jobA"
    assert master["8"]["inputs"]["enabled_worker_ids"] == ["w1", "w2"]

    worker = pt.apply_participant_overrides(
        p,
        pt.ParticipantInfo(
            is_worker=True, worker_index=1, worker_id="w2",
            master_url="http://127.0.0.1:8188", job_ids={"8": "jobA"},
        ),
    )
    assert worker["5"]["inputs"]["worker_index"] == 1
    assert worker["8"]["inputs"]["master_url"] == "http://127.0.0.1:8188"
    # non-distributed nodes untouched
    assert worker["6"]["inputs"] == p["6"]["inputs"]


def test_distributed_value_override_coercion():
    p = {
        "1": {
            "class_type": "DistributedValue",
            "inputs": {"value": "10", "overrides": {"_type": "INT", "2": "99", "1": "bad"}},
        }
    }
    w2 = pt.apply_participant_overrides(
        p, pt.ParticipantInfo(is_worker=True, worker_index=1, worker_id="w2")
    )
    assert w2["1"]["inputs"]["value"] == 99
    # coercion failure keeps the base value
    w1 = pt.apply_participant_overrides(
        p, pt.ParticipantInfo(is_worker=True, worker_index=0, worker_id="w1")
    )
    assert w1["1"]["inputs"]["value"] == "10"
    # master untouched
    m = pt.apply_participant_overrides(p, pt.ParticipantInfo(is_worker=False))
    assert m["1"]["inputs"]["value"] == "10"
