"""Loader + model-sampling nodes: the separate-file workflow surface
(UNETLoader / CLIPLoader family / EmptySD3LatentImage / ModelSampling*)
and a fully assembled Flux-style workflow through the executor."""

import dataclasses

import numpy as np
import pytest

from comfyui_distributed_tpu.graph import ExecutionContext, GraphExecutor
from comfyui_distributed_tpu.graph.nodes_loaders import (
    CLIPLoader,
    DualCLIPLoader,
    EmptySD3LatentImage,
    ModelSamplingDiscrete,
    ModelSamplingFlux,
    ModelSamplingSD3,
    TripleCLIPLoader,
    UNETLoader,
)
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.ops import samplers as smp

pytestmark = pytest.mark.slow


def _ctx():
    return ExecutionContext()


def test_unet_loader_caches_and_strips_extension():
    ctx = _ctx()
    (a,) = UNETLoader().load_unet("tiny-unet.safetensors", context=ctx)
    (b,) = UNETLoader().load_unet("tiny-unet", context=ctx)
    assert a is b  # cached under the stem
    assert a.vae is None and set(a.params) == {"unet"}


def test_clip_loader_type_validation():
    with pytest.raises(ValueError, match="stable_diffusion"):
        CLIPLoader().load_clip("tiny-te", type="flux", context=_ctx())
    with pytest.raises(ValueError, match="sdxl, flux, or sd3"):
        DualCLIPLoader().load_clip(
            "tiny-te", "tiny-te-g", type="stable_diffusion", context=_ctx()
        )


def test_dual_clip_loader_flux_underscore_names():
    """Workflow values carry filenames with underscores; the stems
    normalize onto registry hyphens."""
    (c,) = DualCLIPLoader().load_clip(
        "tiny_te.safetensors", "tiny_t5_shared.safetensors", type="flux",
        context=_ctx(),
    )
    assert c.te_name == "tiny-t5-shared"
    assert c.te2_name == "tiny-te"


def test_triple_clip_loader_sd3():
    (c,) = TripleCLIPLoader().load_clip(
        "tiny-te-l", "tiny-te-g", "tiny-t5-sd3", context=_ctx()
    )
    cond = pl.encode_text_pooled(c, ["x"])
    assert cond.pooled is not None
    assert c.te3_name == "tiny-t5-sd3"


def test_empty_sd3_latent_is_16ch_placeholder():
    (lat,) = EmptySD3LatentImage().generate(64, 32, 2)
    assert lat["samples"].shape == (2, 4, 8, 16)
    assert lat["empty"] and lat["width"] == 64 and lat["height"] == 32


def test_model_sampling_discrete_overrides_parameterization():
    b = pl.load_unet("tiny-unet")
    assert pl.model_schedule_info(b)[0] == "eps"
    (v,) = ModelSamplingDiscrete().patch(b, "v_prediction", False)
    assert pl.model_schedule_info(v)[0] == "v"
    # the original bundle is untouched (replace, not mutate)
    assert pl.model_schedule_info(b)[0] == "eps"
    with pytest.raises(ValueError, match="sampling must be"):
        ModelSamplingDiscrete().patch(b, "lcm", False)
    with pytest.raises(ValueError, match="zsnr"):
        ModelSamplingDiscrete().patch(b, "eps", True)


def test_model_sampling_sd3_sets_shift():
    b = pl.load_unet("tiny-sd3")
    (patched,) = ModelSamplingSD3().patch(b, shift=5.0)
    assert pl.model_schedule_info(patched) == ("flow", 5.0)
    # shift reshapes the sigma grid
    base = np.asarray(smp.get_model_sigmas("flow", "normal", 4,
                                           flow_shift=3.0))
    new = np.asarray(smp.get_model_sigmas("flow", "normal", 4,
                                          flow_shift=5.0))
    assert not np.allclose(base, new)
    with pytest.raises(ValueError, match="flow-matching"):
        ModelSamplingSD3().patch(pl.load_unet("tiny-unet"), shift=5.0)


def test_model_sampling_flux_resolution_dependent():
    b = pl.load_unet("tiny-flux")
    (at_256,) = ModelSamplingFlux().patch(b, 1.15, 0.5, 256, 256)
    # 256x256 → 256 tokens → mu = base_shift → shift = e^0.5
    assert pl.model_schedule_info(at_256)[1] == pytest.approx(
        np.exp(0.5), rel=1e-6
    )
    (at_1024,) = ModelSamplingFlux().patch(b, 1.15, 0.5, 1024, 1024)
    # 1024x1024 → 4096 tokens → mu = max_shift
    assert pl.model_schedule_info(at_1024)[1] == pytest.approx(
        np.exp(1.15), rel=1e-6
    )


def test_assembled_flux_workflow_through_executor():
    """UNETLoader + DualCLIPLoader + VAELoader + ModelSamplingFlux +
    custom sampling — the published-Flux-workflow shape — runs end to
    end through the graph executor."""
    prompt = {
        "u": {"class_type": "UNETLoader",
              "inputs": {"unet_name": "tiny-flux"}},
        "c": {"class_type": "DualCLIPLoader",
              "inputs": {"clip_name1": "tiny-te",
                         "clip_name2": "tiny-t5-shared", "type": "flux"}},
        "v": {"class_type": "VAELoader",
              "inputs": {"vae_name": "tiny-vae-flux"}},
        "ms": {"class_type": "ModelSamplingFlux",
               "inputs": {"model": ["u", 0], "max_shift": 1.15,
                          "base_shift": 0.5, "width": 32, "height": 32}},
        "p": {"class_type": "CLIPTextEncode",
              "inputs": {"text": "x", "clip": ["c", 0]}},
        "g": {"class_type": "FluxGuidance",
              "inputs": {"conditioning": ["p", 0], "guidance": 3.5}},
        "el": {"class_type": "EmptySD3LatentImage",
               "inputs": {"width": 32, "height": 32, "batch_size": 1}},
        "no": {"class_type": "RandomNoise", "inputs": {"noise_seed": 3}},
        "gd": {"class_type": "BasicGuider",
               "inputs": {"model": ["ms", 0], "conditioning": ["g", 0]}},
        "sm": {"class_type": "KSamplerSelect",
               "inputs": {"sampler_name": "euler"}},
        "sg": {"class_type": "BasicScheduler",
               "inputs": {"model": ["ms", 0], "scheduler": "simple",
                          "steps": 2, "denoise": 1.0}},
        "ks": {"class_type": "SamplerCustomAdvanced",
               "inputs": {"noise": ["no", 0], "guider": ["gd", 0],
                          "sampler": ["sm", 0], "sigmas": ["sg", 0],
                          "latent_image": ["el", 0]}},
        "d": {"class_type": "VAEDecode",
              "inputs": {"samples": ["ks", 0], "vae": ["v", 0]}},
        "o": {"class_type": "PreviewImage", "inputs": {"images": ["d", 0]}},
    }
    outs = GraphExecutor(_ctx()).execute(prompt)
    img = np.asarray(outs["o"][0]["images"])
    assert img.shape == (1, 32, 32, 3)
    assert np.all(np.isfinite(img))
