"""CLIPVisionLoader / CLIPVisionEncode / unCLIPConditioning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_loaders import (
    CLIPVisionEncode,
    CLIPVisionLoader,
    ClipVisionOutput,
    UnCLIPConditioning,
)
from comfyui_distributed_tpu.ops.conditioning import Conditioning


@pytest.mark.fast
def test_unclip_conditioning_attaches_fields():
    cond = Conditioning(context=jnp.zeros((1, 4, 8)))
    out_tokens = jnp.ones((1, 17, 48))
    (patched,) = UnCLIPConditioning().apply_adm(
        cond, ClipVisionOutput(tokens=out_tokens), strength=0.5,
        noise_augmentation=0.1,
    )
    assert patched.unclip_strength == 0.5
    assert patched.unclip_noise_aug == 0.1
    np.testing.assert_array_equal(
        np.asarray(patched.unclip_embeds), np.asarray(out_tokens)
    )
    # the original is untouched (map_conditioning clones)
    assert cond.unclip_embeds is None


@pytest.mark.fast
def test_unclip_fields_survive_pytree_roundtrip():
    c = Conditioning(
        context=jnp.zeros((1, 4, 8)),
        unclip_embeds=jnp.ones((1, 17, 48)),
        unclip_strength=0.25,
        unclip_noise_aug=0.5,
    )
    leaves, treedef = jax.tree_util.tree_flatten(c)
    c2 = jax.tree_util.tree_unflatten(treedef, leaves)
    assert c2.unclip_strength == 0.25
    assert c2.unclip_noise_aug == 0.5
    np.testing.assert_array_equal(
        np.asarray(c2.unclip_embeds), np.asarray(c.unclip_embeds)
    )


@pytest.mark.fast
def test_unclip_conditioning_rejected_at_sampling():
    from comfyui_distributed_tpu.ops import samplers as smp

    model_fn = lambda x, sigma, cond: x  # noqa: E731
    guided = smp.cfg_model(model_fn, 2.0)
    x = jnp.zeros((1, 2, 2, 1))
    sig = jnp.ones((1,))
    pos = Conditioning(
        context=jnp.zeros((1, 4, 8)), unclip_embeds=jnp.ones((1, 3, 8))
    )
    neg = Conditioning(context=jnp.zeros((1, 4, 8)))
    with pytest.raises(ValueError, match="unCLIP"):
        guided(x, sig, (pos, neg))


@pytest.mark.fast
def test_clip_vision_encode_rejects_non_center_crop():
    class _Stub:
        def encode(self, img):  # pragma: no cover - never reached
            return img

    with pytest.raises(ValueError):
        CLIPVisionEncode().encode(_Stub(), jnp.zeros((1, 8, 8, 3)),
                                  crop="none")


@pytest.mark.slow
def test_clip_vision_loader_encode_end_to_end():
    (bundle,) = CLIPVisionLoader().load_clip("tiny-clip-vision")
    img = jnp.linspace(0, 1, 2 * 40 * 24 * 3, dtype=jnp.float32).reshape(
        2, 40, 24, 3
    )
    (out,) = CLIPVisionEncode().encode(bundle, img)
    toks = np.asarray(out.tokens)
    assert toks.shape[0] == 2 and toks.ndim == 3
    assert np.isfinite(toks).all()
    # caching: same context dict returns the same bundle object
    class _Ctx:
        pipelines = {}

    ctx = _Ctx()
    (b1,) = CLIPVisionLoader().load_clip("tiny-clip-vision", context=ctx)
    (b2,) = CLIPVisionLoader().load_clip("tiny-clip-vision", context=ctx)
    assert b1 is b2
