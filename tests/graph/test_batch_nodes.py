"""Batch/concat utility nodes (ConditioningConcat, ImageBatch,
RepeatLatentBatch — ComfyUI substrate parity) and their end-to-end
compatibility with the sampler."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    ConditioningConcat,
    ImageBatch,
    KSampler,
    RepeatLatentBatch,
)
from comfyui_distributed_tpu.models import pipeline as pl

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    return pl.load_pipeline("tiny-unet", seed=0)


def test_conditioning_concat_token_axis(bundle):
    a = pl.encode_text_pooled(bundle, ["first prompt"])
    b = pl.encode_text_pooled(bundle, ["second prompt"])
    a_tokens_before = a.context.shape[1]
    (c,) = ConditioningConcat().concat(a, b)
    assert c.context.shape[1] == a_tokens_before + b.context.shape[1]
    np.testing.assert_array_equal(
        np.asarray(c.context[:, :a_tokens_before]), np.asarray(a.context)
    )
    # clone semantics: the input object is untouched
    assert a.context.shape[1] == a_tokens_before
    # pooled rides from conditioning_to
    np.testing.assert_array_equal(np.asarray(c.pooled), np.asarray(a.pooled))
    # the concatenated conditioning samples end to end
    neg = pl.encode_text_pooled(bundle, [""])
    (out,) = KSampler().sample(
        bundle, 1, 2, 7.0, "euler", "karras", c, neg,
        {"samples": jnp.zeros((1, 8, 8, 4))}, denoise=1.0,
    )
    assert np.isfinite(np.asarray(out["samples"])).all()


def test_image_batch_resizes_second():
    a = jnp.full((1, 32, 32, 3), 0.25)
    b = jnp.full((2, 16, 16, 3), 0.75)
    (out,) = ImageBatch().batch(a, b)
    assert out.shape == (3, 32, 32, 3)
    np.testing.assert_allclose(np.asarray(out[0]), 0.25)
    np.testing.assert_allclose(np.asarray(out[1:]), 0.75, atol=1e-6)


def test_image_batch_center_crops_aspect_mismatch():
    """Aspect mismatch center-crops before resizing (reference
    common_upscale 'center'), never stretches: marker stripes outside
    the central crop must vanish."""
    a = jnp.zeros((1, 16, 16, 3))
    wide = np.zeros((1, 16, 32, 3), np.float32)
    wide[:, :, :8] = 1.0  # stripe in the crop-discarded left margin
    (out,) = ImageBatch().batch(a, jnp.asarray(wide))
    assert out.shape == (2, 16, 16, 3)
    # the central 16 columns of the wide image are all zero
    np.testing.assert_allclose(np.asarray(out[1]), 0.0, atol=1e-6)


def test_image_crop_clamps():
    from comfyui_distributed_tpu.graph.nodes_core import ImageCrop

    img = jnp.arange(1 * 16 * 16 * 3, dtype=jnp.float32).reshape(1, 16, 16, 3)
    (out,) = ImageCrop().crop(img, width=8, height=4, x=6, y=2)
    assert out.shape == (1, 4, 8, 3)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(img[:, 2:6, 6:14]))
    # out-of-range request clamps to the frame
    (edge,) = ImageCrop().crop(img, width=100, height=100, x=12, y=12)
    assert edge.shape == (1, 4, 4, 3)


def test_latent_composite_paste_and_feather():
    from comfyui_distributed_tpu.graph.nodes_core import LatentComposite

    dst = {"samples": jnp.zeros((1, 8, 8, 4))}
    src = {"samples": jnp.ones((1, 4, 4, 4))}
    (out,) = LatentComposite().composite(dst, src, x=16, y=16, feather=0)
    got = np.asarray(out["samples"])
    np.testing.assert_array_equal(got[:, 2:6, 2:6], 1.0)  # pasted
    np.testing.assert_array_equal(got[:, :2, :], 0.0)     # untouched
    # feather ramps the interior edges instead of a hard seam
    (fe,) = LatentComposite().composite(dst, src, x=16, y=16, feather=16)
    gf = np.asarray(fe["samples"])
    assert 0.0 < gf[0, 2, 3, 0] < 1.0  # ramped top edge
    assert gf[0, 3, 3, 0] > gf[0, 2, 3, 0]  # ramp rises inward
    # a paste flush with the border keeps full weight on that edge
    (fl,) = LatentComposite().composite(dst, src, x=0, y=0, feather=16)
    gl = np.asarray(fl["samples"])
    np.testing.assert_allclose(gl[0, 0, 0], 1.0)
    # fully out-of-range paste is a no-op
    (off,) = LatentComposite().composite(dst, src, x=640, y=0)
    np.testing.assert_array_equal(np.asarray(off["samples"]), 0.0)


def test_image_scale_by_and_invert():
    from comfyui_distributed_tpu.graph.nodes_core import (
        ImageInvert,
        ImageScaleBy,
    )

    img = jnp.full((1, 16, 16, 3), 0.25)
    (up,) = ImageScaleBy().scale(img, "bilinear", 1.5)
    assert up.shape == (1, 24, 24, 3)
    np.testing.assert_allclose(np.asarray(up), 0.25, atol=1e-6)
    (inv,) = ImageInvert().invert(img)
    np.testing.assert_allclose(np.asarray(inv), 0.75, atol=1e-6)


def test_repeat_latent_batch():
    z = jnp.arange(2 * 4 * 4 * 4, dtype=jnp.float32).reshape(2, 4, 4, 4)
    mask = jnp.ones((2, 4, 4, 1))
    (out,) = RepeatLatentBatch().repeat(
        {"samples": z, "noise_mask": mask}, amount=3
    )
    assert out["samples"].shape == (6, 4, 4, 4)
    np.testing.assert_array_equal(
        np.asarray(out["samples"][2:4]), np.asarray(z)
    )
    assert out["noise_mask"].shape == (6, 4, 4, 1)
    # amount < 1 clamps to a no-op copy
    (one,) = RepeatLatentBatch().repeat({"samples": z}, amount=0)
    assert one["samples"].shape == z.shape
