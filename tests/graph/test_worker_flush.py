"""Worker-side payload flushing: size-aware chunking and interrupt
propagation in the USDU worker loop."""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph import ExecutionContext
from comfyui_distributed_tpu.graph.usdu_elastic import run_worker_loop
from comfyui_distributed_tpu.models import pipeline as pl


@pytest.fixture(scope="module")
def bundle():
    return pl.load_pipeline("tiny-unet", seed=0)


class RecordingClient:
    def __init__(self, tile_ids):
        self.tile_ids = list(tile_ids)
        self.flushes = []

    def poll_ready(self):
        return True

    def request_tile(self):
        if not self.tile_ids:
            return None
        return {"tile_idx": self.tile_ids.pop(0)}

    def submit_tiles(self, entries, is_final):
        self.flushes.append((list(entries), is_final))

    def heartbeat(self):
        pass


def test_flush_batches_on_max_batch(bundle, monkeypatch):
    """MAX_TILE_BATCH forces intermediate flushes before the final one."""
    import comfyui_distributed_tpu.graph.usdu_elastic as elastic

    monkeypatch.setattr(elastic, "MAX_TILE_BATCH", 2)
    img = jnp.asarray(np.random.default_rng(0).random((1, 96, 96, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    client = RecordingClient([0, 1, 2, 3])  # 2x upscale of 96 → 4 tiles of 96px
    run_worker_loop(
        bundle, img, pos, neg, job_id="f", worker_id="w", master_url="",
        upscale_by=2.0, tile=96, padding=16, steps=1, sampler="euler",
        scheduler="karras", cfg=1.0, denoise=0.3, seed=0, client=client,
    )
    # 4 tiles with flush threshold 2: two intermediate + one final flush
    assert [len(e) for e, _ in client.flushes] == [2, 2, 0]
    assert [f for _, f in client.flushes] == [False, False, True]


def test_interrupt_stops_worker_loop(bundle):
    img = jnp.asarray(np.random.default_rng(1).random((1, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    ctx = ExecutionContext()
    ctx.interrupt_event.set()
    client = RecordingClient([0, 1, 2, 3])
    with pytest.raises(InterruptedError):
        run_worker_loop(
            bundle, img, pos, neg, job_id="i", worker_id="w", master_url="",
            upscale_by=2.0, tile=64, padding=16, steps=1, sampler="euler",
            scheduler="karras", cfg=1.0, denoise=0.3, seed=0,
            context=ctx, client=client,
        )
    # no tiles processed after the interrupt
    assert client.flushes == []
