"""KSamplerAdvanced (windowed-schedule sampler): schedule slicing,
two-pass composition, no-noise refine pass, leftover noise, masked
sampling, and the per-participant mesh path — the ComfyUI node
two-pass workflows depend on."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    EmptyLatentImage,
    KSampler,
    KSamplerAdvanced,
    SeedSpec,
)
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.ops import samplers as smp

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    """tiny-unet with the zero-initialized leaves perturbed: the
    SD-faithful zero-init out_conv makes a random-init model emit
    eps == 0 exactly, which would let every schedule window produce
    the same (unmoved) latents and trivialize trajectory tests."""
    import jax

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(123)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


def _cond(bundle):
    return (
        pl.encode_text_pooled(bundle, ["p"]),
        pl.encode_text_pooled(bundle, [""]),
    )


def test_advanced_window_sigmas_slices_full_grid():
    full = np.asarray(smp.get_sigmas("karras", 8))
    w = np.asarray(
        pl.advanced_window_sigmas("eps", "karras", 8, 2, 5, False, 3.0)
    )
    np.testing.assert_array_equal(w, full[2:6])
    # force_full_denoise pins the final sigma to 0 despite stopping early
    wf = np.asarray(
        pl.advanced_window_sigmas("eps", "karras", 8, 2, 5, True, 3.0)
    )
    np.testing.assert_array_equal(wf[:-1], full[2:5])
    assert wf[-1] == 0.0
    # out-of-range clamps; end >= steps reaches the terminal 0
    w2 = np.asarray(
        pl.advanced_window_sigmas("eps", "karras", 8, 0, 10000, False, 3.0)
    )
    np.testing.assert_array_equal(w2, full)


def test_two_pass_composition_matches_single(bundle):
    """pass1 (leftover noise, steps 0..2) + pass2 (no added noise,
    steps 2..4) walks the same euler trajectory as one full 4-step
    KSampler run; cross-program XLA rounding bounds the comparison."""
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    (single,) = KSampler().sample(
        bundle, 5, 4, 7.0, "euler", "karras", pos, neg, el, denoise=1.0
    )
    (p1,) = KSamplerAdvanced().sample(
        bundle, "enable", 5, 4, 7.0, "euler", "karras", pos, neg, el,
        start_at_step=0, end_at_step=2,
        return_with_leftover_noise="enable",
    )
    (p2,) = KSamplerAdvanced().sample(
        bundle, "disable", 5, 4, 7.0, "euler", "karras", pos, neg, p1,
        start_at_step=2, end_at_step=4,
        return_with_leftover_noise="disable",
    )
    np.testing.assert_allclose(
        np.asarray(p2["samples"]), np.asarray(single["samples"]), atol=5e-2
    )
    # the intermediate latent is a different point on the trajectory
    # (still carries sigma[2]-level noise), not the finished sample
    assert not np.array_equal(
        np.asarray(p1["samples"]), np.asarray(single["samples"])
    )
    # and the trajectory genuinely moves latents (the fixture undoes
    # the zero-init eps degeneracy)
    assert not np.array_equal(
        np.asarray(p1["samples"]), np.asarray(p2["samples"])
    )


def test_no_noise_empty_window_is_identity(bundle):
    rng = np.random.default_rng(4)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    pos, neg = _cond(bundle)
    (out,) = KSamplerAdvanced().sample(
        bundle, "disable", 1, 4, 7.0, "euler", "karras", pos, neg,
        {"samples": z}, start_at_step=2, end_at_step=2,
    )
    np.testing.assert_array_equal(np.asarray(out["samples"]), np.asarray(z))


def test_empty_window_with_mask_preserves_region(bundle):
    """start == end with add_noise=enable and a noise_mask: no steps
    run, but the mask contract still holds — the preserved region
    comes back intact, not noised."""
    rng = np.random.default_rng(6)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, 4:] = 1.0
    latent = {"samples": z, "noise_mask": jnp.asarray(mask)[..., None]}
    pos, neg = _cond(bundle)
    (out,) = KSamplerAdvanced().sample(
        bundle, "enable", 3, 4, 7.0, "euler", "karras", pos, neg, latent,
        start_at_step=1, end_at_step=1,
    )
    got = np.asarray(out["samples"])
    np.testing.assert_array_equal(got[:, :4], np.asarray(z)[:, :4])
    assert not np.array_equal(got[:, 4:], np.asarray(z)[:, 4:])  # noised


def test_flag_validation(bundle):
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    with pytest.raises(ValueError, match="add_noise"):
        KSamplerAdvanced().sample(
            bundle, "yes", 1, 2, 7.0, "euler", "karras", pos, neg, el
        )
    with pytest.raises(ValueError, match="return_with_leftover_noise"):
        KSamplerAdvanced().sample(
            bundle, "enable", 1, 2, 7.0, "euler", "karras", pos, neg, el,
            return_with_leftover_noise="maybe",
        )


def test_masked_advanced_keeps_unmasked_region(bundle):
    rng = np.random.default_rng(0)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, :, 4:] = 1.0
    latent = {"samples": z, "noise_mask": jnp.asarray(mask)[..., None]}
    pos, neg = _cond(bundle)
    (out,) = KSamplerAdvanced().sample(
        bundle, "enable", 3, 4, 7.0, "euler", "karras", pos, neg, latent,
        start_at_step=0, end_at_step=4,
    )
    got = np.asarray(out["samples"])
    np.testing.assert_array_equal(got[:, :, :4], np.asarray(z)[:, :, :4])
    assert not np.allclose(got[:, :, 4:], np.asarray(z)[:, :, 4:])
    assert "noise_mask" in out  # extras propagate for chained passes


def test_mesh_parallel_advanced(bundle):
    """SeedSpec + mesh: the advanced sampler runs the same SPMD
    participant fan-out as KSampler, on its windowed schedule."""
    from types import SimpleNamespace

    from comfyui_distributed_tpu.parallel import build_mesh

    mesh = build_mesh({"data": 8})
    ctx = SimpleNamespace(mesh=mesh)
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    pos, neg = _cond(bundle)
    (out,) = KSamplerAdvanced().sample(
        bundle, "enable", SeedSpec(base_seed=9, per_participant=True),
        4, 7.0, "euler", "karras", pos, neg, el,
        start_at_step=0, end_at_step=4, context=ctx,
    )
    got = np.asarray(out["samples"])
    assert got.shape[0] == 8
    assert out.get("participant_major")
    sums = {round(float(got[i].sum()), 4) for i in range(8)}
    assert len(sums) == 8  # distinct participants

    # chained refine pass WITHOUT noise must not fan out again: a
    # deterministic pass replicated across chips would stack identical
    # copies and square the batch — it runs as one batched program
    (refined,) = KSamplerAdvanced().sample(
        bundle, "disable", SeedSpec(base_seed=9, per_participant=True),
        4, 7.0, "euler", "karras", pos, neg, out,
        start_at_step=2, end_at_step=4, context=ctx,
    )
    ref = np.asarray(refined["samples"])
    assert ref.shape[0] == 8  # same batch, not 64
    ref_sums = {round(float(ref[i].sum()), 4) for i in range(8)}
    assert len(ref_sums) == 8  # diversity preserved


def test_no_noise_masked_pin_uses_zero_noise(bundle):
    """add_noise=disable + noise_mask: the preserved region is pinned
    to the ORIGINAL latents (zero pin noise — ComfyUI disable_noise),
    and survives bit-exactly."""
    rng = np.random.default_rng(8)
    z = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    mask = np.zeros((1, 8, 8), np.float32)
    mask[:, 4:] = 1.0
    latent = {"samples": z, "noise_mask": jnp.asarray(mask)[..., None]}
    pos, neg = _cond(bundle)
    (out,) = KSamplerAdvanced().sample(
        bundle, "disable", 3, 4, 7.0, "euler", "karras", pos, neg, latent,
        start_at_step=2, end_at_step=4,
    )
    got = np.asarray(out["samples"])
    np.testing.assert_array_equal(got[:, :4], np.asarray(z)[:, :4])
    assert not np.array_equal(got[:, 4:], np.asarray(z)[:, 4:])
