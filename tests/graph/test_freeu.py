"""FreeU / FreeU_V2: backbone half-channel scaling + Fourier low-pass
skip scaling at the up-path joins (config-carried patch, no new
weights)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_core import (
    EmptyLatentImage,
    KSampler,
)
from comfyui_distributed_tpu.graph.nodes_loaders import FreeU, FreeU_V2
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.models.unet import _fourier_lowpass_scale

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    import jax

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(123)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


def test_fourier_lowpass_identity_at_scale_one():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(1, 8, 8, 4)), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(_fourier_lowpass_scale(x, 1, 1.0)), np.asarray(x),
        atol=1e-5,
    )


def test_fourier_lowpass_scales_dc():
    """Scaling the center box by 0 removes (most of) the mean — the DC
    component lives in the low-frequency box."""
    x = jnp.ones((1, 8, 8, 1), jnp.float32)
    out = np.asarray(_fourier_lowpass_scale(x, 1, 0.0))
    assert abs(out.mean()) < 1e-5


def test_freeu_changes_sampling_and_preserves_params(bundle):
    pos = pl.encode_text_pooled(bundle, ["forest"])
    neg = pl.encode_text_pooled(bundle, [""])
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    (base,) = KSampler().sample(
        bundle, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    (patched,) = FreeU().patch(bundle, 1.5, 1.6, 0.9, 0.2)
    assert patched.params is bundle.params  # no new weights
    assert patched.unet.config.freeu == (1.5, 1.6, 0.9, 0.2, False)
    (out,) = KSampler().sample(
        patched, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    assert not np.allclose(
        np.asarray(base["samples"]), np.asarray(out["samples"])
    )
    # v2 (adaptive) differs from v1 at the same knobs
    (p2,) = FreeU_V2().patch(bundle, 1.5, 1.6, 0.9, 0.2)
    (out2,) = KSampler().sample(
        p2, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    assert not np.allclose(
        np.asarray(out["samples"]), np.asarray(out2["samples"])
    )


def test_freeu_neutral_knobs_are_near_identity(bundle):
    """b=1, s=1 is the identity transform (exact at the _apply_freeu
    math level — see the fourier identity test). At the trajectory
    level the FFT round-trip through the bf16 compute dtype injects
    rounding the chaotic tiny net amplifies, so the check is relative:
    the neutral patch moves the output far less than active knobs."""
    pos = pl.encode_text_pooled(bundle, ["forest"])
    neg = pl.encode_text_pooled(bundle, [""])
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    (base,) = KSampler().sample(
        bundle, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    (neutral,) = FreeU().patch(bundle, 1.0, 1.0, 1.0, 1.0)
    (out_n,) = KSampler().sample(
        neutral, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    (active,) = FreeU().patch(bundle, 1.5, 1.6, 0.5, 0.2)
    (out_a,) = KSampler().sample(
        active, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    d_neutral = np.abs(
        np.asarray(base["samples"]) - np.asarray(out_n["samples"])
    ).mean()
    d_active = np.abs(
        np.asarray(base["samples"]) - np.asarray(out_a["samples"])
    ).mean()
    assert d_neutral < 0.5 * d_active


def test_freeu_rejects_non_unet_families():
    flux = pl.load_pipeline("tiny-flux", seed=0)
    with pytest.raises(ValueError, match="SD-class UNets"):
        FreeU().patch(flux, 1.1, 1.2, 0.9, 0.2)
