"""RescaleCFG, SDTurboScheduler, ThresholdMask, alpha split/join,
ConditioningSetAreaPercentage — the round-5 second widening batch."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_controlnet import (
    ConditioningSetAreaPercentage,
    SkipLayerGuidanceSD3,
)
from comfyui_distributed_tpu.graph.nodes_core import (
    EmptyLatentImage,
    KSampler,
)
from comfyui_distributed_tpu.graph.nodes_custom_sampling import (
    SDTurboScheduler,
)
from comfyui_distributed_tpu.graph.nodes_loaders import RescaleCFG
from comfyui_distributed_tpu.graph.nodes_mask import (
    JoinImageWithAlpha,
    SplitImageWithAlpha,
    ThresholdMask,
)
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.ops import samplers as smp

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def bundle():
    import jax

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(123)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    return b


def test_rescale_cfg_changes_sampling(bundle):
    pos = pl.encode_text_pooled(bundle, ["forest"])
    neg = pl.encode_text_pooled(bundle, [""])
    (el,) = EmptyLatentImage().generate(32, 32, 1)
    (base,) = KSampler().sample(
        bundle, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    (patched,) = RescaleCFG().patch(bundle, 0.7)
    (rescaled,) = KSampler().sample(
        patched, 5, 2, 7.0, "euler", "karras", pos, neg, el
    )
    assert not np.allclose(
        np.asarray(base["samples"]), np.asarray(rescaled["samples"])
    )
    # multiplier 0 keeps plain-CFG MATH; identical program structure ⇒
    # results equal (the lerp reduces to x0_cfg exactly)
    (zero,) = RescaleCFG().patch(bundle, 0.0)
    m_plain = smp.rescale_cfg_model(
        pl._make_model_fn(bundle, bundle.params), 7.0, 0.0
    )
    m_cfg = smp.cfg_model(pl._make_model_fn(bundle, bundle.params), 7.0)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1, 16, 16, 4)), jnp.float32)
    sig = jnp.asarray([5.0])
    np.testing.assert_allclose(
        np.asarray(m_plain(x, sig, (pos, neg))),
        np.asarray(m_cfg(x, sig, (pos, neg))),
        atol=2e-2,  # eps-space round trip through x0 at bf16 compute
    )
    assert zero.cfg_rescale == 0.0


def test_rescale_cfg_slg_exclusive():
    s3 = pl.load_pipeline("tiny-sd3", seed=0)
    (slg,) = SkipLayerGuidanceSD3().skip_guidance(s3, "0", 3.0, 0.0, 0.2)
    with pytest.raises(ValueError, match="SkipLayerGuidanceSD3"):
        RescaleCFG().patch(slg, 0.7)
    (rescaled,) = RescaleCFG().patch(s3, 0.7)
    with pytest.raises(ValueError, match="RescaleCFG"):
        SkipLayerGuidanceSD3().skip_guidance(rescaled, "0", 3.0, 0.0, 0.2)


def test_sd_turbo_scheduler_decades(bundle):
    (sig,) = SDTurboScheduler().get_sigmas(bundle, 2, 1.0)
    table = smp._vp_sigmas()
    np.testing.assert_allclose(
        np.asarray(sig), [table[999], table[899], 0.0], rtol=1e-6
    )
    # denoise 0.5 starts five decades in
    (sig2,) = SDTurboScheduler().get_sigmas(bundle, 1, 0.5)
    np.testing.assert_allclose(np.asarray(sig2), [table[499], 0.0], rtol=1e-6)
    with pytest.raises(ValueError, match="1-10"):
        SDTurboScheduler().get_sigmas(bundle, 11, 1.0)
    flux = pl.load_pipeline("tiny-flux", seed=0)
    with pytest.raises(ValueError, match="flow-family"):
        SDTurboScheduler().get_sigmas(flux, 1, 1.0)


def test_threshold_mask():
    m = jnp.asarray([[0.2, 0.5, 0.8]])[None]
    (out,) = ThresholdMask().image_to_mask(m, 0.5)
    np.testing.assert_array_equal(np.asarray(out), [[[0.0, 0.0, 1.0]]])


def test_alpha_join_split_roundtrip():
    rng = np.random.default_rng(0)
    rgb = jnp.asarray(rng.uniform(size=(1, 8, 8, 3)), jnp.float32)
    mask = jnp.asarray(rng.uniform(size=(1, 8, 8)), jnp.float32)
    (rgba,) = JoinImageWithAlpha().join_image_with_alpha(rgb, mask)
    assert rgba.shape == (1, 8, 8, 4)
    out_rgb, out_mask = SplitImageWithAlpha().split_image_with_alpha(rgba)
    np.testing.assert_allclose(np.asarray(out_rgb), np.asarray(rgb))
    np.testing.assert_allclose(
        np.asarray(out_mask), np.asarray(mask), atol=1e-6
    )
    # alpha-less input: zero mask
    _, m0 = SplitImageWithAlpha().split_image_with_alpha(rgb)
    assert not np.any(np.asarray(m0))


def test_area_percentage_carries_fractions(bundle):
    """Fractions ride as the ('percentage', ...) marker and resolve
    against the ACTUAL frame wherever it is known — no canvas-size
    inputs (reference workflows don't carry any)."""
    from comfyui_distributed_tpu.ops.conditioning import resolve_area

    cond = pl.encode_text_pooled(bundle, ["x"])
    (out,) = ConditioningSetAreaPercentage().set_area(
        cond, 0.5, 0.25, 0.5, 0.0, 0.9
    )
    assert out.area == ("percentage", 0.25, 0.5, 0.0, 0.5)
    assert out.strength == 0.9
    # resolution against a 1024x512 frame
    assert resolve_area(out.area, 512, 1024) == (128, 512, 0, 512)
    # pixel areas pass through untouched
    assert resolve_area((8, 8, 0, 0), 512, 1024) == (8, 8, 0, 0)
