"""Round-5 custom-sampling additions: PolyexponentialScheduler,
BetaSamplingScheduler, DualCFGGuider (+ smp.dual_cfg_model math)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.nodes_custom_sampling import (
    BasicGuider,
    BetaSamplingScheduler,
    DualCFGGuider,
    ExponentialScheduler,
    PolyexponentialScheduler,
    RandomNoise,
    SamplerCustomAdvanced,
    SamplerSpec,
)
from comfyui_distributed_tpu.graph.nodes_core import SeedSpec
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.ops import samplers as smp


@pytest.mark.fast
def test_polyexponential_rho1_equals_exponential():
    (poly,) = PolyexponentialScheduler().get_sigmas(
        steps=12, sigma_max=10.0, sigma_min=0.05, rho=1.0
    )
    (expo,) = ExponentialScheduler().get_sigmas(
        steps=12, sigma_max=10.0, sigma_min=0.05
    )
    np.testing.assert_allclose(np.asarray(poly), np.asarray(expo), rtol=1e-6)


@pytest.mark.fast
def test_polyexponential_rho_warps_toward_min():
    (s1,) = PolyexponentialScheduler().get_sigmas(steps=10, rho=1.0)
    (s3,) = PolyexponentialScheduler().get_sigmas(steps=10, rho=3.0)
    a1, a3 = np.asarray(s1), np.asarray(s3)
    assert a1.shape == a3.shape == (11,)
    assert a1[-1] == a3[-1] == 0.0
    assert np.all(np.diff(a3[:-1]) < 0)  # strictly descending
    # rho>1 spends the interior closer to sigma_min
    assert a3[5] < a1[5]
    # endpoints match
    np.testing.assert_allclose(a3[0], a1[0], rtol=1e-6)


@pytest.mark.fast
def test_beta_sampling_default_matches_beta_scheduler():
    """alpha=beta=0.6 must reproduce scheduler='beta' exactly (same
    table, same quantile spacing, same collision handling)."""
    (node_sig,) = BetaSamplingScheduler().get_sigmas(
        _vp_stub(), steps=15, alpha=0.6, beta=0.6
    )
    ref = smp.get_sigmas("beta", 15)
    np.testing.assert_allclose(
        np.asarray(node_sig), np.asarray(ref), rtol=1e-6
    )


def _vp_stub():
    """Minimal MODEL stub for model_schedule_info: an eps-family
    bundle without loading weights."""
    b = object.__new__(pl.PipelineBundle)
    b.model_name = "tiny-unet"
    b.parameterization_override = None
    b.flow_shift_override = None
    return b


@pytest.mark.fast
def test_dual_cfg_model_math_regular():
    """regular: out = [n + c2*(e2 - n)] + c1*(e1 - e2), with a toy
    model that returns its conditioning."""
    model_fn = lambda x, sigma, cond: cond  # noqa: E731
    x = jnp.zeros((1, 2, 2, 1))
    sig = jnp.ones((1,))
    p1 = jnp.full_like(x, 3.0)
    p2 = jnp.full_like(x, 2.0)
    n = jnp.full_like(x, 1.0)
    dual = smp.dual_cfg_model(model_fn, 2.0, 0.5)
    out = dual(x, sig, ((p1, p2), n))
    mid = 1.0 + 0.5 * (2.0 - 1.0)  # 1.5
    expect = mid + 2.0 * (3.0 - 2.0)  # 3.5
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


@pytest.mark.fast
def test_dual_cfg_model_math_nested():
    """nested: inner = e2 + c1*(e1 - e2); out = n + c2*(inner - n)."""
    model_fn = lambda x, sigma, cond: cond  # noqa: E731
    x = jnp.zeros((1, 2, 2, 1))
    sig = jnp.ones((1,))
    p1 = jnp.full_like(x, 3.0)
    p2 = jnp.full_like(x, 2.0)
    n = jnp.full_like(x, 1.0)
    dual = smp.dual_cfg_model(model_fn, 2.0, 0.5, nested=True)
    out = dual(x, sig, ((p1, p2), n))
    inner = 2.0 + 2.0 * (3.0 - 2.0)  # 4.0
    expect = 1.0 + 0.5 * (inner - 1.0)  # 2.5
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


@pytest.mark.fast
def test_dual_cfg_regular_cond2_eq_negative_is_plain_cfg():
    """regular with cond2 == negative must reduce exactly to CFG over
    (cond1, negative) at cfg_conds, for any cfg_cond2_negative."""
    model_fn = lambda x, sigma, cond: cond * 2.0  # noqa: E731
    x = jnp.zeros((1, 2, 2, 1))
    sig = jnp.ones((1,))
    p1 = jnp.full_like(x, 3.0)
    n = jnp.full_like(x, 1.0)
    dual = smp.dual_cfg_model(model_fn, 7.0, 123.0)
    out = dual(x, sig, ((p1, n), n))
    cfg = smp.cfg_model(model_fn, 7.0)
    ref = cfg(x, sig, (p1, n))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


@pytest.mark.fast
def test_dual_cfg_rejects_slg_and_rescale_combos():
    b = _vp_stub()
    b.cfg_rescale = 0.7
    b.slg = None
    b.dual_cfg = pl.DualCFGSpec(cfg_cond2_negative=1.0)
    with pytest.raises(ValueError):
        pl.guided_model(b, {}, 1.0)


@pytest.mark.slow
def test_dual_cfg_guider_end_to_end():
    """regular style with cond2 == negative must reproduce CFGGuider
    at cfg_conds through the full SamplerCustomAdvanced path; a
    genuinely dual run stays finite and diverges from it."""
    import jax

    from comfyui_distributed_tpu.graph.nodes_custom_sampling import (
        CFGGuider,
    )

    b = pl.load_pipeline("tiny-unet", seed=0)
    rng = np.random.default_rng(7)

    def fix(x):
        arr = np.asarray(x)
        if arr.size and not np.any(arr):
            return jnp.asarray(
                (rng.normal(size=arr.shape) * 0.05).astype(arr.dtype)
            )
        return x

    b.params = dict(
        b.params, unet=jax.tree_util.tree_map(fix, b.params["unet"])
    )
    pos = pl.encode_text(b, ["a castle"])
    alt = pl.encode_text(b, ["a forest"])
    neg = pl.encode_text(b, [""])
    sig = smp.get_sigmas("karras", 3)
    latent = {"samples": jnp.zeros((1, 8, 8, 4))}
    (noise,) = RandomNoise().get_noise(5)
    (g_dual,) = DualCFGGuider().get_guider(
        b, pos, neg, neg, cfg_conds=4.0, cfg_cond2_negative=9.0
    )
    out_dual, _ = SamplerCustomAdvanced().sample(
        noise, g_dual, SamplerSpec("euler"), sig, latent
    )
    (g_cfg,) = CFGGuider().get_guider(b, pos, neg, cfg=4.0)
    out_cfg, _ = SamplerCustomAdvanced().sample(
        noise, g_cfg, SamplerSpec("euler"), sig, latent
    )
    # 3B-batched vs 2B-batched bf16 evals differ by fusion noise only
    # (measured 7e-4 on ~20-magnitude latents; exact 0.0 single-device)
    np.testing.assert_allclose(
        np.asarray(out_dual["samples"]),
        np.asarray(out_cfg["samples"]),
        atol=5e-3,
    )
    # a genuinely dual run (distinct cond2, both styles) is finite
    # and distinct
    for style in ("regular", "nested"):
        (g2,) = DualCFGGuider().get_guider(
            b, pos, alt, neg, cfg_conds=4.0, cfg_cond2_negative=3.0,
            style=style,
        )
        out2, _ = SamplerCustomAdvanced().sample(
            noise, g2, SamplerSpec("euler"), sig, latent
        )
        a2 = np.asarray(out2["samples"])
        assert np.isfinite(a2).all()
        assert not np.allclose(a2, np.asarray(out_cfg["samples"]))
    with pytest.raises(ValueError):
        DualCFGGuider().get_guider(b, pos, alt, neg, style="inverted")
