"""Elastic-tier USDU loops, hermetic (scripted client, real JobStore,
real asyncio queues — the reference's fake-comms test pattern)."""

import threading
import types

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph import ExecutionContext
from comfyui_distributed_tpu.graph.usdu_elastic import (
    run_master_elastic,
    run_worker_loop,
)
from comfyui_distributed_tpu.jobs import JobStore
from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.ops import upscale as up
from comfyui_distributed_tpu.utils.async_helpers import run_async_in_server_loop


@pytest.fixture(scope="module")
def bundle():
    return pl.load_pipeline("tiny-unet", seed=0)


class ScriptedClient:
    """Replays a fixed tile sequence; records submissions/heartbeats."""

    def __init__(self, tile_ids):
        self.tile_ids = list(tile_ids)
        self.submitted = []
        self.flushes = []
        self.heartbeats = 0
        self.ready_polls = 0

    def poll_ready(self):
        self.ready_polls += 1
        return True

    def request_tile(self):
        if not self.tile_ids:
            return None
        return {"tile_idx": self.tile_ids.pop(0), "estimated_remaining": len(self.tile_ids)}

    def submit_tiles(self, entries, is_final):
        self.submitted.extend(entries)
        self.flushes.append((len(entries), is_final))

    def heartbeat(self):
        self.heartbeats += 1


def test_worker_loop_processes_scripted_tiles(bundle):
    img = jnp.asarray(np.random.default_rng(0).random((1, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    client = ScriptedClient([0, 2, 3])
    run_worker_loop(
        bundle, img, pos, neg, job_id="j", worker_id="w1",
        master_url="", upscale_by=2.0, tile=64, padding=16, steps=1,
        sampler="euler", scheduler="karras", cfg=1.0, denoise=0.3, seed=4,
        client=client,
    )
    # at least one heartbeat per processed tile; the pipeline's I/O
    # stage may add idle beats while a device batch (or the first
    # compile) is in flight — that's the liveness the master relies on
    assert client.heartbeats >= 3
    assert {e["tile_idx"] for e in client.submitted} == {0, 2, 3}
    assert client.flushes[-1][1] is True  # final flush marked
    entry = client.submitted[0]
    assert entry["image"].startswith("data:image/png;base64,")
    assert entry["extracted_w"] == entry["extracted_h"]


def test_master_elastic_with_live_worker_submissions(bundle, server_loop):
    """Master runs its loop while a thread plays a worker that pulls
    from the same store and submits PNG results."""
    img = jnp.asarray(np.random.default_rng(1).random((1, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    store = JobStore()
    server = types.SimpleNamespace(job_store=store)
    ctx = ExecutionContext(server=server, config={"workers": []})

    from comfyui_distributed_tpu.graph.usdu_elastic import _jit_tile_processor
    from comfyui_distributed_tpu.ops import tiles as tile_ops
    from comfyui_distributed_tpu.utils import image as img_utils
    import jax

    _, _, grid = up.plan_grid(64, 64, 2.0, 64, 16)
    assert grid.num_tiles == 4

    def worker_thread():
        # identical preprocessing to the master
        upscaled = jnp.clip(
            jax.image.resize(img, (1, 128, 128, 3), method="cubic"), 0, 1
        )
        extracted = tile_ops.extract_tiles(upscaled, grid)
        process = _jit_tile_processor(bundle, grid, 1, "euler", "karras", 1.0, 0.3)
        key = jax.random.key(9)
        while True:
            tile_idx = run_async_in_server_loop(
                store.pull_task("job1", "w1", timeout=0.5)
            )
            if tile_idx is None:
                break
            tkey = jax.random.fold_in(key, tile_idx)
            result = process(
                bundle.params, extracted[tile_idx], tkey, pos, neg,
                grid.positions_array()[tile_idx],
            )
            arr = img_utils.ensure_numpy(result)
            payload = [
                {"batch_idx": i, "image": img_utils.encode_image_data_url(arr[i])}
                for i in range(arr.shape[0])
            ]
            run_async_in_server_loop(
                store.submit_result("job1", "w1", tile_idx, payload)
            )

    # let the master create the job, then the worker joins
    t = threading.Thread(target=worker_thread, daemon=True)

    orig_init = store.init_tile_job

    async def init_and_start(*args, **kwargs):
        job = await orig_init(*args, **kwargs)
        if not t.is_alive():
            t.start()
        return job

    store.init_tile_job = init_and_start

    out = run_master_elastic(
        bundle, img, pos, neg, job_id="job1", enabled_worker_ids=["w1"],
        upscale_by=2.0, tile=64, padding=16, steps=1, sampler="euler",
        scheduler="karras", cfg=1.0, denoise=0.3, seed=9, context=ctx,
    )
    t.join(timeout=30)
    assert out.shape == (1, 128, 128, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_master_batched_grants_amortize_latency_stream(
    bundle, monkeypatch, server_loop
):
    """The master's own tile share runs as batched grants; the latency
    sink (watchdog straggler signal + placement EWMA) must still see
    one AMORTIZED per-tile sample per tile — never one per-batch lump
    followed by near-zero flush gaps."""
    from comfyui_distributed_tpu.scheduler.placement import PlacementPolicy

    monkeypatch.setenv("CDT_TILE_BATCH", "4")
    img = jnp.asarray(np.random.default_rng(3).random((1, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    store = JobStore()
    # a placement policy makes pull_tasks grant multi-tile batches to
    # the master (base_batch=4, no samples → uniform speed)
    store.placement = PlacementPolicy(
        min_samples=1, base_batch=4, max_batch=4, tail_tiles=0
    )
    samples: list[tuple[str, float]] = []
    store.latency_sink = lambda wid, sec: samples.append((wid, sec))
    server = types.SimpleNamespace(job_store=store)
    ctx = ExecutionContext(server=server, config={"workers": []})

    out = run_master_elastic(
        bundle, img, pos, neg, job_id="job3", enabled_worker_ids=[],
        upscale_by=2.0, tile=64, padding=16, steps=1, sampler="euler",
        scheduler="karras", cfg=1.0, denoise=0.3, seed=3, context=ctx,
    )
    assert out.shape == (1, 128, 128, 3)
    master_samples = [sec for wid, sec in samples if wid == "master"]
    assert len(master_samples) == 4  # one per tile, not one per batch
    # amortized evenly: a 4-tile flush records four equal shares, so
    # the spread within one flush is ~zero (no near-zero poison gaps)
    grouped = {round(s, 9) for s in master_samples}
    assert len(grouped) <= 2, master_samples


def test_master_elastic_requeues_dead_worker(bundle, monkeypatch, server_loop):
    """A worker pulls a tile and dies; the master's timeout path must
    requeue and locally complete it."""
    from comfyui_distributed_tpu.utils import config as cfg_mod

    monkeypatch.setattr(cfg_mod, "get_worker_timeout_seconds", lambda path=None: 0.5)
    import comfyui_distributed_tpu.graph.usdu_elastic as elastic

    img = jnp.asarray(np.random.default_rng(2).random((1, 64, 64, 3)), jnp.float32)
    pos = pl.encode_text(bundle, ["p"])
    neg = pl.encode_text(bundle, [""])
    store = JobStore()
    server = types.SimpleNamespace(job_store=store)
    ctx = ExecutionContext(server=server, config={"workers": []})

    orig_init = store.init_tile_job

    async def init_then_steal(*args, **kwargs):
        job = await orig_init(*args, **kwargs)
        # dead worker grabs a tile and never returns it
        await store.pull_task("job2", "zombie", timeout=1)
        return job

    store.init_tile_job = init_then_steal

    out = run_master_elastic(
        bundle, img, pos, neg, job_id="job2", enabled_worker_ids=["zombie"],
        upscale_by=2.0, tile=64, padding=16, steps=1, sampler="euler",
        scheduler="karras", cfg=1.0, denoise=0.3, seed=2, context=ctx,
    )
    assert out.shape == (1, 128, 128, 3)
    assert np.isfinite(np.asarray(out)).all()
