"""WanImageToVideo node: the reference's WAN i2v workflow role at the
node layer (native i2v conditioning for i2v-layout models, frame-0
clamp fallback otherwise)."""

import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph import ExecutionContext
from comfyui_distributed_tpu.graph.nodes_video import (
    VideoCheckpointLoader,
    WanImageToVideo,
)

pytestmark = pytest.mark.slow


def test_i2v_node_generates_frames():
    ctx = ExecutionContext()
    (bundle, _clip, _vae) = VideoCheckpointLoader().load("tiny-dit-i2v", context=ctx)
    rng = np.random.default_rng(0)
    img = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    (frames,) = WanImageToVideo().generate(
        bundle, img, "pan right", frames=5, steps=2, cfg=5.0, seed=3,
        context=ctx,
    )
    assert frames.shape == (5, 32, 32, 3)
    assert np.all(np.isfinite(np.asarray(frames)))


def test_i2v_node_validates_stride_for_i2v_models():
    ctx = ExecutionContext()
    (bundle, _c, _v) = VideoCheckpointLoader().load("tiny-dit-i2v", context=ctx)
    img = jnp.zeros((1, 32, 32, 3))
    with pytest.raises(ValueError, match="4n\\+1"):
        WanImageToVideo().generate(bundle, img, "x", frames=6, steps=1,
                                   context=ctx)


def test_i2v_node_rejects_mesh_fanout():
    """A per-participant SeedSpec on a mesh errors loudly instead of
    silently collapsing to one seed (fan-out for i2v rides the elastic
    tier)."""
    from types import SimpleNamespace

    from comfyui_distributed_tpu.graph.nodes_core import SeedSpec
    from comfyui_distributed_tpu.parallel import build_mesh

    ctx = ExecutionContext()
    (bundle, _c, _v) = VideoCheckpointLoader().load("tiny-dit-i2v", context=ctx)
    mesh_ctx = SimpleNamespace(mesh=build_mesh({"data": 8}))
    with pytest.raises(ValueError, match="elastic tier"):
        WanImageToVideo().generate(
            bundle, jnp.zeros((1, 32, 32, 3)), "x", frames=5, steps=1,
            seed=SeedSpec(base_seed=1, per_participant=True),
            context=mesh_ctx,
        )


def test_i2v_node_fallback_allows_any_frames():
    """Non-i2v-layout video models take the frame-0 clamp fallback,
    which has no causal-VAE stride constraint."""
    ctx = ExecutionContext()
    (bundle, _c, _v) = VideoCheckpointLoader().load("tiny-dit", context=ctx)
    rng = np.random.default_rng(1)
    img = jnp.asarray(rng.uniform(size=(1, 32, 32, 3)), jnp.float32)
    (frames,) = WanImageToVideo().generate(
        bundle, img, "x", frames=4, steps=1, cfg=1.0, seed=0, context=ctx
    )
    assert frames.shape[0] == 4
