"""Device-resident hot path (graph/batch_executor.py): buffer donation
on the batched step program, the persistent-latent stash that serves
preemption resumes without a host round-trip, and the precision-lane
knob. The bit-identity contract: resume-from-device ≡ resume-from-host
≡ uninterrupted, for jitted AND eager processors."""

import threading
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.graph.batch_executor import (
    CrossJobExecutor,
    XJobHandle,
)
from comfyui_distributed_tpu.parallel.seeds import fold_job_key
from comfyui_distributed_tpu.utils.constants import precision_for_lane

N_STEPS = 4


def _make_proc(n_steps=N_STEPS, signature=("stub",), jit=False):
    def init(params, tile, key):
        return tile + 0.0

    def step(params, x, key, pos, neg, yx, i):
        ki = jax.random.fold_in(key, i)
        return x + 0.01 * jax.random.normal(ki, x.shape) + 0.001 * pos

    def finish(params, x):
        return jnp.round(jnp.clip(x, 0.0, 1.0) * 255.0) / 255.0

    return types.SimpleNamespace(
        init=init,
        step=jax.jit(step) if jit else step,
        finish=finish,
        n_steps=n_steps,
        signature=tuple(signature),
    )


class _FakeMaster:
    def __init__(self, n_tiles, grant_size=64):
        self.pending = list(range(n_tiles))
        self.ckpts = {}
        self.grant_size = grant_size
        self.released = []
        self.lock = threading.Lock()

    def pull(self):
        with self.lock:
            if not self.pending:
                return None
            grant = self.pending[: self.grant_size]
            self.pending = self.pending[self.grant_size:]
            cks = {t: self.ckpts.pop(t) for t in list(self.ckpts) if t in grant}
            return {"tile_idxs": grant, "checkpoints": cks}

    def release(self, idxs, cks):
        with self.lock:
            self.released.append((list(idxs), dict(cks)))
            self.pending = sorted(set(self.pending) | set(idxs))
            self.ckpts.update(cks)


def _make_job(job_id, n_tiles, seed, *, proc, master=None, priority=0, flag=None):
    master = master or _FakeMaster(n_tiles)
    rng = np.random.default_rng(seed)
    extracted = jnp.asarray(rng.random((n_tiles, 4, 4, 3)), jnp.float32)
    positions = jnp.zeros((n_tiles, 2), jnp.int32)
    outs = {}

    def emit(idx, arr):
        outs[int(idx)] = np.asarray(arr)

    handle = XJobHandle(
        job_id=job_id,
        proc=proc,
        params=None,
        extracted=extracted,
        positions=positions,
        pos=jnp.float32(seed),
        neg=jnp.float32(0),
        base_key=fold_job_key(jax.random.key(seed), job_id),
        pull=master.pull,
        emit=emit,
        flush=lambda final: None,
        release=master.release,
        preempt_check=(lambda: flag.is_set()) if flag is not None else None,
        priority=priority,
    )
    return handle, outs, master


def _solo(job_id, n_tiles, seed, *, proc, k_max=8):
    ex = CrossJobExecutor(k_max=k_max)
    handle, outs, _ = _make_job(job_id, n_tiles, seed, proc=proc)
    ex.register(handle)
    ex.run()
    return outs


def _batch_inputs(n, shape=(4, 4, 3)):
    xs = jnp.asarray(np.random.default_rng(0).random((n, *shape)), jnp.float32)
    keys = jax.random.split(jax.random.key(0), n)
    poss = jnp.zeros((n,), jnp.float32)
    negs = jnp.zeros((n,), jnp.float32)
    yxs = jnp.zeros((n, 2), jnp.int32)
    steps = jnp.zeros((n,), jnp.int32)
    return xs, keys, poss, negs, yxs, steps


# --------------------------------------------------------------------------
# buffer donation
# --------------------------------------------------------------------------


def test_vstep_jitted_program_aliases_and_consumes_latents():
    """The batched step must carry an input_output_alias for the
    stacked latents (XLA reuses the buffer) and DELETE the donated
    input after the call — the one-allocation-per-step invariant."""
    ex = CrossJobExecutor(k_max=4)
    proc = _make_proc(jit=True)
    fn = ex._vstep(("sig-jit",), proc.step)
    xs, keys, poss, negs, yxs, steps = _batch_inputs(2)
    lowered = fn.lower(None, xs, keys, poss, negs, yxs, steps)
    assert "input_output_alias" in lowered.compile().as_text()
    out = jax.block_until_ready(fn(None, xs, keys, poss, negs, yxs, steps))
    assert xs.is_deleted()
    assert out.shape == (2, 4, 4, 3)


def test_vstep_compiles_once_across_steps():
    """One compiled program per batch shape: the traced step index
    (jnp.take on sigmas in production) means step 0..n share it."""
    ex = CrossJobExecutor(k_max=4)
    proc = _make_proc(jit=True)
    fn = ex._vstep(("sig-count",), proc.step)
    for i in range(3):
        xs, keys, poss, negs, yxs, _ = _batch_inputs(2)
        steps = jnp.full((2,), i, jnp.int32)
        jax.block_until_ready(fn(None, xs, keys, poss, negs, yxs, steps))
    assert fn._cache_size() == 1
    # the executor-level cache hands back the same program object
    assert ex._vstep(("sig-count",), proc.step) is fn


def test_vstep_eager_stub_stays_undonated():
    """Raw Python stubs (the chaos parity suite) must not be donated:
    donation is a jit concept, and the stub's inputs stay readable."""
    ex = CrossJobExecutor(k_max=4)
    proc = _make_proc(jit=False)
    fn = ex._vstep(("sig-eager",), proc.step)
    assert not hasattr(fn, "lower")
    xs, keys, poss, negs, yxs, steps = _batch_inputs(2)
    jax.block_until_ready(fn(None, xs, keys, poss, negs, yxs, steps))
    assert not xs.is_deleted()


# --------------------------------------------------------------------------
# persistent-latent stash: resume bit-identity
# --------------------------------------------------------------------------


@pytest.mark.parametrize("jit", [False, True], ids=["eager", "jitted"])
@pytest.mark.parametrize("device_resident", [True, False], ids=["device", "host"])
def test_resume_modes_bit_identical_to_uninterrupted(
    monkeypatch, device_resident, jit
):
    """Evict mid-trajectory, resume, and compare against the
    uninterrupted solo run: byte-equal in BOTH resume modes. The
    device stash serves the resume when enabled (the checkpoint stays
    a cold spill); disabling it falls back to checkpoint decode."""
    monkeypatch.setenv(
        "CDT_XJOB_DEVICE_RESIDENT", "1" if device_resident else "0"
    )
    proc = _make_proc(n_steps=5, jit=jit)
    flag = threading.Event()

    class _RelentingMaster(_FakeMaster):
        def release(self, idxs, cks):
            super().release(idxs, cks)
            flag.clear()  # pressure lifts once the eviction lands

    master = _RelentingMaster(4)
    ex = CrossJobExecutor(k_max=8)
    handle, outs, _ = _make_job(
        "job", 4, 3, proc=proc, master=master, flag=flag
    )
    ex.register(handle)
    count = {"n": 0}
    orig = ex._step_batch

    def hooked(batch):
        orig(batch)
        count["n"] += 1
        if count["n"] == 2:
            flag.set()

    ex._step_batch = hooked
    stats = ex.run()
    assert stats["preempt_evictions"] == 4
    if device_resident:
        assert stats["resumes_device"] == 4
        assert stats["resumes_checkpoint"] == 0
    else:
        assert stats["resumes_device"] == 0
        assert stats["resumes_checkpoint"] == 4
    assert stats["resumes_recompute"] == 0
    solo = _solo("job", 4, 3, proc=_make_proc(n_steps=5, jit=jit))
    for i in range(4):
        np.testing.assert_array_equal(outs[i], solo[i])


def test_device_and_host_resume_agree(monkeypatch):
    """resume-from-device ≡ resume-from-host directly (not only via
    the solo reference): the stash latent IS the array the checkpoint
    was encoded from, so the two modes cannot diverge."""

    def run(mode):
        monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT", mode)
        proc = _make_proc(n_steps=5)
        flag = threading.Event()

        class _RelentingMaster(_FakeMaster):
            def release(self, idxs, cks):
                super().release(idxs, cks)
                flag.clear()

        ex = CrossJobExecutor(k_max=8)
        handle, outs, _ = _make_job(
            "job", 3, 7, proc=proc, master=_RelentingMaster(3), flag=flag
        )
        ex.register(handle)
        count = {"n": 0}
        orig = ex._step_batch

        def hooked(batch):
            orig(batch)
            count["n"] += 1
            if count["n"] == 2:
                flag.set()

        ex._step_batch = hooked
        ex.run()
        return outs

    device_outs = run("1")
    host_outs = run("0")
    assert set(device_outs) == set(host_outs) == {0, 1, 2}
    for i in device_outs:
        np.testing.assert_array_equal(device_outs[i], host_outs[i])


# --------------------------------------------------------------------------
# stash mechanics: budget, FIFO eviction, step guard
# --------------------------------------------------------------------------


def _half_mb():
    return jnp.zeros((131072,), jnp.float32)  # 512 KiB


def test_stash_budget_evicts_fifo(monkeypatch):
    monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT", "1")
    monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT_MB", "1")
    ex = CrossJobExecutor(k_max=2)
    ex._stash_put("job", 0, _half_mb(), 2)
    ex._stash_put("job", 1, _half_mb(), 2)
    assert ex._device_stash_bytes == 2 * 524288
    # the third entry exceeds the 1 MiB budget: the OLDEST goes
    ex._stash_put("job", 2, _half_mb(), 2)
    assert ex._stash_take("job", 0, 2) is None
    assert ex._stash_take("job", 1, 2) is not None
    assert ex._stash_take("job", 2, 2) is not None
    assert ex._device_stash_bytes == 0


def test_stash_step_mismatch_misses(monkeypatch):
    """A stale stash entry (checkpoint advanced past it) must MISS —
    the checkpoint payload is the authoritative resume instruction."""
    monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT", "1")
    ex = CrossJobExecutor(k_max=2)
    ex._stash_put("job", 0, _half_mb(), 2)
    assert ex._stash_take("job", 0, 3) is None
    # the mismatched entry is consumed, not retried
    assert ex._device_stash == {}
    assert ex._device_stash_bytes == 0


def test_stash_oversized_latent_never_parked(monkeypatch):
    monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT", "1")
    monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT_MB", "1")
    ex = CrossJobExecutor(k_max=2)
    ex._stash_put("job", 0, jnp.zeros((524288,), jnp.float32), 1)  # 2 MiB
    assert ex._device_stash == {}


def test_stash_knob_off_is_noop(monkeypatch):
    monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT", "0")
    ex = CrossJobExecutor(k_max=2)
    ex._stash_put("job", 0, _half_mb(), 1)
    assert ex._device_stash == {}
    assert ex._stash_take("job", 0, 1) is None


def test_job_failure_drops_stash(monkeypatch):
    monkeypatch.setenv("CDT_XJOB_DEVICE_RESIDENT", "1")
    ex = CrossJobExecutor(k_max=2)
    ex._stash_put("a", 0, _half_mb(), 1)
    ex._stash_put("a", 1, _half_mb(), 1)
    ex._stash_put("b", 0, _half_mb(), 1)
    ex._drop_job_stash("a")
    assert list(ex._device_stash) == [("b", 0)]
    assert ex._device_stash_bytes == 524288


# --------------------------------------------------------------------------
# precision lane routing
# --------------------------------------------------------------------------


def test_precision_for_lane_routing(monkeypatch):
    monkeypatch.delenv("CDT_BF16_LANES", raising=False)
    assert precision_for_lane("background") == "f32"
    monkeypatch.setenv("CDT_BF16_LANES", "background, batch")
    assert precision_for_lane("background") == "bf16"
    assert precision_for_lane("batch") == "bf16"
    assert precision_for_lane("interactive") == "f32"
    assert precision_for_lane("") == "f32"
    monkeypatch.setenv("CDT_BF16_LANES", "*")
    assert precision_for_lane("interactive") == "bf16"
    assert precision_for_lane("") == "bf16"
