"""CrossJobExecutor unit suite (graph/batch_executor.py): mixed-batch
determinism (jitted + eager), fill accounting, signature separation,
priority ordering, step-boundary preemption with checkpoint/recompute
resume, and per-job error isolation."""

import threading
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.graph.batch_executor import (
    CrossJobExecutor,
    XJobHandle,
)
from comfyui_distributed_tpu.ops.stepwise import encode_checkpoint
from comfyui_distributed_tpu.parallel.seeds import fold_job_key

N_STEPS = 3


def _make_proc(n_steps=N_STEPS, signature=("stub",), jit=False):
    def init(params, tile, key):
        return tile + 0.0

    def step(params, x, key, pos, neg, yx, i):
        ki = jax.random.fold_in(key, i)
        return x + 0.01 * jax.random.normal(ki, x.shape) + 0.001 * pos

    def finish(params, x):
        return jnp.round(jnp.clip(x, 0.0, 1.0) * 255.0) / 255.0

    return types.SimpleNamespace(
        init=init,
        step=jax.jit(step) if jit else step,
        finish=finish,
        n_steps=n_steps,
        signature=tuple(signature),
    )


class _FakeMaster:
    """Store stand-in for one job: pending queue + checkpoint buffer
    with the release/pull contract of the real JobStore."""

    def __init__(self, n_tiles, grant_size=64):
        self.pending = list(range(n_tiles))
        self.ckpts = {}
        self.grant_size = grant_size
        self.released = []  # (idxs, checkpoints) calls, in order
        self.lock = threading.Lock()

    def pull(self):
        with self.lock:
            if not self.pending:
                return None
            grant = self.pending[: self.grant_size]
            self.pending = self.pending[self.grant_size:]
            cks = {t: self.ckpts.pop(t) for t in list(self.ckpts) if t in grant}
            return {"tile_idxs": grant, "checkpoints": cks}

    def release(self, idxs, cks):
        with self.lock:
            self.released.append((list(idxs), dict(cks)))
            self.pending = sorted(set(self.pending) | set(idxs))
            self.ckpts.update(cks)


def _make_job(
    job_id, n_tiles, seed, *, proc, master=None, priority=0, flag=None,
    emit_hook=None,
):
    master = master or _FakeMaster(n_tiles)
    rng = np.random.default_rng(seed)
    extracted = jnp.asarray(rng.random((n_tiles, 4, 4, 3)), jnp.float32)
    positions = jnp.zeros((n_tiles, 2), jnp.int32)
    outs = {}

    def emit(idx, arr):
        outs[int(idx)] = np.asarray(arr)
        if emit_hook is not None:
            emit_hook(int(idx))

    handle = XJobHandle(
        job_id=job_id,
        proc=proc,
        params=None,
        extracted=extracted,
        positions=positions,
        pos=jnp.float32(seed),
        neg=jnp.float32(0),
        base_key=fold_job_key(jax.random.key(seed), job_id),
        pull=master.pull,
        emit=emit,
        flush=lambda final: None,
        release=master.release,
        preempt_check=(lambda: flag.is_set()) if flag is not None else None,
        priority=priority,
    )
    return handle, outs, master


def _solo(job_id, n_tiles, seed, *, proc, k_max=8):
    ex = CrossJobExecutor(k_max=k_max)
    handle, outs, _ = _make_job(job_id, n_tiles, seed, proc=proc)
    ex.register(handle)
    ex.run()
    return outs


# --------------------------------------------------------------------------
# determinism
# --------------------------------------------------------------------------


@pytest.mark.parametrize("jit", [False, True], ids=["eager", "jitted"])
def test_mixed_batch_bit_identical_to_solo(jit):
    """A tile's output is bit-identical whether sampled alone, batched
    with its own job, or batched with another tenant's tiles."""
    proc = _make_proc(jit=jit)
    ex = CrossJobExecutor(k_max=4)
    h1, o1, _ = _make_job("job-a", 3, 1, proc=proc)
    h2, o2, _ = _make_job("job-b", 3, 2, proc=proc)
    ex.register(h1)
    ex.register(h2)
    ex.run()
    solo_a = _solo("job-a", 3, 1, proc=proc)
    solo_b = _solo("job-b", 3, 2, proc=proc)
    for i in range(3):
        np.testing.assert_array_equal(o1[i], solo_a[i])
        np.testing.assert_array_equal(o2[i], solo_b[i])


def test_same_seed_jobs_diverge_by_job_id():
    """Two jobs sharing the user seed draw INDEPENDENT streams: the
    fold key gains the job id (parallel/seeds.fold_job_key)."""
    proc = _make_proc()
    a = _solo("job-a", 2, 7, proc=proc)
    b = _solo("job-b", 2, 7, proc=proc)
    assert not np.array_equal(a[0], b[0])


# --------------------------------------------------------------------------
# batching / fill accounting
# --------------------------------------------------------------------------


def test_fill_ratio_accounting_cross_vs_per_job():
    """Two 3-tile jobs, k_max=4: per-job batches pad every dispatch
    3 → 4 (fill 0.75); cross-job batches keep the 4-slot device full
    from the combined ready queue (fill 1.0)."""
    proc = _make_proc()
    mixed = CrossJobExecutor(k_max=4)
    for jid, seed in (("a", 1), ("b", 2)):
        mixed.register(_make_job(jid, 3, seed, proc=proc)[0])
    stats = mixed.run()
    assert stats["tiles"] == 6
    assert stats["slots_real"] == 6 * N_STEPS

    perjob = CrossJobExecutor(k_max=4, cross_job=False)
    for jid, seed in (("a", 1), ("b", 2)):
        perjob.register(_make_job(jid, 3, seed, proc=proc)[0])
    stats_pj = perjob.run()
    assert stats_pj["tiles"] == 6
    assert mixed.fill_ratio() > perjob.fill_ratio()
    assert perjob.fill_ratio() == pytest.approx(0.75)
    assert mixed.fill_ratio() == pytest.approx(1.0)


def test_bucket_multiple_rounds_buckets():
    ex = CrossJobExecutor(k_max=8, bucket_multiple=4)
    assert ex.buckets == (4, 8)
    assert ex._bucket_for(1) == 4
    assert ex._bucket_for(5) == 8


def test_signatures_never_mix_in_one_dispatch():
    proc_a = _make_proc(signature=("sig-a",))
    proc_b = _make_proc(signature=("sig-b",))
    ex = CrossJobExecutor(k_max=8)
    ex.register(_make_job("a", 2, 1, proc=proc_a)[0])
    ex.register(_make_job("b", 2, 2, proc=proc_b)[0])
    seen = []
    orig = ex._step_batch

    def spy(batch):
        seen.append({it.job.proc.signature for it in batch})
        orig(batch)

    ex._step_batch = spy
    stats = ex.run()
    assert stats["tiles"] == 4
    assert seen and all(len(sigs) == 1 for sigs in seen)


# --------------------------------------------------------------------------
# priority + preemption
# --------------------------------------------------------------------------


def test_priority_orders_completions():
    proc = _make_proc()
    ex = CrossJobExecutor(k_max=2)
    ex.register(_make_job("low", 2, 1, proc=proc, priority=5)[0])
    ex.register(_make_job("high", 2, 2, proc=proc, priority=0)[0])
    ex.run()
    order = [jid for jid, _ in ex.completion_order]
    assert order[:2] == ["high", "high"]


@pytest.mark.parametrize("device_resident", [True, False], ids=["device", "host"])
def test_preempt_evicts_checkpoints_and_resumes_bit_identical(
    monkeypatch, device_resident
):
    monkeypatch.setenv(
        "CDT_XJOB_DEVICE_RESIDENT", "1" if device_resident else "0"
    )
    proc = _make_proc(n_steps=5)
    flag = threading.Event()
    master = _FakeMaster(6)
    ex = CrossJobExecutor(k_max=8)
    handle, outs, _ = _make_job(
        "batch", 6, 3, proc=proc, master=master, priority=10, flag=flag
    )
    ex.register(handle)
    count = {"n": 0}
    orig = ex._step_batch

    def hooked(batch):
        orig(batch)
        count["n"] += 1
        if count["n"] == 2:
            hp, op, _ = _make_job("prem", 2, 4, proc=proc, priority=0)
            hooked.prem = op

            def clear_when_prem_done(idx, _op=op):
                if len(_op) >= 2:
                    flag.clear()

            hp.emit = _wrap_emit(hp.emit, op, flag)
            ex.register(hp)
            flag.set()

    def _wrap_emit(emit, op, flag):
        def wrapped(idx, arr):
            emit(idx, arr)
            if len(op) >= 2:
                flag.clear()
        return wrapped

    ex._step_batch = hooked
    stats = ex.run()
    assert stats["preempt_evictions"] == 6
    if device_resident:
        # parked device latents served every resume; the host
        # checkpoint stayed a cold spill (never decoded)
        assert stats["resumes_device"] == 6
        assert stats["resumes_checkpoint"] == 0
    else:
        assert stats["resumes_checkpoint"] == 6
        assert stats["resumes_device"] == 0
    assert stats["resumes_recompute"] == 0
    # the release carried mid-trajectory checkpoints through the
    # release seam (the real return_tiles path in production)
    assert master.released and all(
        cks for _, cks in master.released[:1]
    )
    # premium completed before any remaining batch tile
    order = [jid for jid, _ in ex.completion_order]
    first_prem = order.index("prem")
    assert "batch" not in order[first_prem : first_prem + 2]
    # outputs bit-identical to solo runs despite evict/resume
    solo_b = _solo("batch", 6, 3, proc=_make_proc(n_steps=5))
    for i in range(6):
        np.testing.assert_array_equal(outs[i], solo_b[i])


def test_lost_checkpoint_recomputes_from_zero_bit_identical():
    proc = _make_proc(n_steps=5)
    flag = threading.Event()

    class _AmnesiacMaster(_FakeMaster):
        def release(self, idxs, cks):
            super().release(idxs, {})  # the crash: checkpoints die
            flag.clear()  # preemption pressure lifts post-eviction

    master = _AmnesiacMaster(4)
    ex = CrossJobExecutor(k_max=8)
    handle, outs, _ = _make_job(
        "batch", 4, 3, proc=proc, master=master, flag=flag
    )
    ex.register(handle)
    count = {"n": 0}
    orig = ex._step_batch

    def hooked(batch):
        orig(batch)
        count["n"] += 1
        if count["n"] == 2:
            flag.set()

    ex._step_batch = hooked
    stats = ex.run()
    assert stats["preempt_evictions"] == 4
    assert stats["resumes_recompute"] == 4
    assert stats["resumes_checkpoint"] == 0
    solo = _solo("batch", 4, 3, proc=_make_proc(n_steps=5))
    for i in range(4):
        np.testing.assert_array_equal(outs[i], solo[i])


def test_malformed_checkpoint_drops_to_recompute():
    proc = _make_proc(n_steps=4)
    master = _FakeMaster(2, grant_size=2)
    master.ckpts = {
        0: {"v": 1, "step": 2, "dtype": "float32", "shape": [1], "data": "x"},
        1: encode_checkpoint(np.zeros((1, 4, 4, 3), np.float32), 99),  # >= n
    }
    ex = CrossJobExecutor(k_max=4)
    handle, outs, _ = _make_job("j", 2, 5, proc=proc, master=master)
    ex.register(handle)
    stats = ex.run()
    assert stats["tiles"] == 2
    solo = _solo("j", 2, 5, proc=_make_proc(n_steps=4))
    for i in range(2):
        np.testing.assert_array_equal(outs[i], solo[i])


# --------------------------------------------------------------------------
# error isolation
# --------------------------------------------------------------------------


def test_one_jobs_failure_releases_and_spares_others():
    proc = _make_proc()
    master_bad = _FakeMaster(2)
    ex = CrossJobExecutor(k_max=8)
    bad, _, _ = _make_job("bad", 2, 1, proc=proc, master=master_bad)

    def boom(idx, arr):
        raise RuntimeError("emit exploded")

    bad.emit = boom
    good, good_outs, _ = _make_job("good", 2, 2, proc=proc)
    ex.register(bad)
    ex.register(good)
    # per-job isolation: the failure lands on the BAD handle (its
    # blocking owner re-raises it); the shared driver keeps serving
    # the other jobs and run() completes
    ex.run()
    assert isinstance(bad.error, RuntimeError) and bad.finished.is_set()
    assert good.done and len(good_outs) == 2
    # the failed job's claims went back through the release seam
    assert master_bad.released


# --------------------------------------------------------------------------
# production entries (CDT_XJOB_BATCH wiring)
# --------------------------------------------------------------------------


def _run_xjob_e2e(monkeypatch, job_id, *, device_canvas=False):
    """One delegated-master xjob run against a real JobStore with the
    stub processor; returns the blended canvas as ndarray."""
    from unittest import mock

    from comfyui_distributed_tpu.graph import ExecutionContext
    from comfyui_distributed_tpu.graph import batch_executor as bx
    from comfyui_distributed_tpu.graph import usdu_elastic as elastic
    from comfyui_distributed_tpu.jobs import JobStore
    from comfyui_distributed_tpu.resilience.chaos import (
        _ensure_server_loop,
        _stub_stepwise,
    )

    bx._reset_shared_executor_for_tests()
    monkeypatch.setenv("CDT_XJOB_BATCH", "1")
    monkeypatch.setenv("CDT_DETERMINISTIC_BLEND", "1")
    monkeypatch.setenv("CDT_DEVICE_CANVAS", "1" if device_canvas else "0")
    store = JobStore()
    ctx = ExecutionContext(
        server=types.SimpleNamespace(job_store=store), config={"workers": []}
    )
    bundle = types.SimpleNamespace(params=None)
    image = jnp.asarray(
        np.random.default_rng(0).random((1, 32, 96, 3)), jnp.float32
    )
    pos = neg = jnp.zeros((1, 4, 8), jnp.float32)
    with _ensure_server_loop(), mock.patch(
        "comfyui_distributed_tpu.ops.stepwise.make_stepwise_tile_processor",
        lambda *a, **k: _stub_stepwise(2),
    ), mock.patch.object(
        elastic.config_mod if hasattr(elastic, "config_mod") else __import__(
            "comfyui_distributed_tpu.utils.config", fromlist=["x"]
        ),
        "get_worker_timeout_seconds",
        lambda path=None: 1.0,
    ):
        # the delegation seam: run_master_elastic routes to the xjob
        # entry under the knob + a stepwise-capable sampler
        out = elastic.run_master_elastic(
            bundle, image, pos, neg,
            job_id=job_id,
            enabled_worker_ids=[],
            upscale_by=2.0, tile=64, padding=16,
            steps=2, sampler="euler", scheduler="karras",
            cfg=1.0, denoise=0.3, seed=0, context=ctx,
        )
    out = np.asarray(out)
    # the job settled cleanly at the store
    assert store.tile_jobs == {}
    bx._reset_shared_executor_for_tests()
    return out


def test_run_master_xjob_end_to_end_with_stub(monkeypatch):
    """The delegated master entry drives the shared executor against a
    real JobStore and blends a complete canvas (stub processor)."""
    out = _run_xjob_e2e(monkeypatch, "xjob-e2e")
    assert out.shape == (1, 64, 192, 3)


def test_run_master_xjob_device_canvas_bit_identical(monkeypatch):
    """CDT_DEVICE_CANVAS=1 on the xjob tier: master-local tiles stay
    device-resident (device_emit) and composite on-device with ONE d2h
    flush — bit-identical to the host-canvas run."""
    # same job id both runs: the per-tile noise keys fold it, so the
    # tiles themselves are identical and only the canvas path differs
    host = _run_xjob_e2e(monkeypatch, "xjob-ab")
    device = _run_xjob_e2e(monkeypatch, "xjob-ab", device_canvas=True)
    assert device.shape == (1, 64, 192, 3)
    np.testing.assert_array_equal(host, device)


def test_preempt_learned_from_drained_pull_parks_instead_of_finishing():
    """HTTP clients learn the preempt flag from the SAME response that
    reads as drained: the executor must park the job (and resume it
    when the flag lifts), never final-flush it as complete."""
    proc = _make_proc(n_steps=2)
    flag = threading.Event()
    state = {"phase": "preempted", "beats": 0}
    outs = {}

    def pull():
        # while preempted the master answers drained + preempt (the
        # tiles were evicted); once lifted, the tiles come back
        if state["phase"] == "preempted":
            flag.set()  # the client learned preempt from this response
            return None
        if state["phase"] == "resumed":
            state["phase"] = "drained"
            return {"tile_idxs": [0, 1]}
        return None

    def heartbeat():
        # the production side-channel: a parked worker keeps beating,
        # and the flag lifts from a heartbeat response
        state["beats"] += 1
        if state["phase"] == "preempted" and state["beats"] >= 2:
            state["phase"] = "resumed"
            flag.clear()

    rng = np.random.default_rng(9)
    handle = XJobHandle(
        job_id="parked",
        proc=proc,
        params=None,
        extracted=jnp.asarray(rng.random((2, 4, 4, 3)), jnp.float32),
        positions=jnp.zeros((2, 2), jnp.int32),
        pos=jnp.float32(0),
        neg=jnp.float32(0),
        base_key=fold_job_key(jax.random.key(9), "parked"),
        pull=pull,
        emit=lambda i, a: outs.__setitem__(int(i), np.asarray(a)),
        flush=lambda final: None,
        heartbeat=heartbeat,
        preempt_check=flag.is_set,
    )
    clock = {"t": 0.0}

    def fake_clock():
        # each read advances 0.6s so the 1s heartbeat pacing fires
        # within a few idle rounds instead of real seconds
        clock["t"] += 0.6
        return clock["t"]

    ex = CrossJobExecutor(k_max=4, idle_poll_seconds=0.001, clock=fake_clock)
    ex.register(handle)
    stats = ex.run()
    # the job was NOT finished during the preempt window: it parked,
    # resumed when the flag lifted, and completed its tiles
    assert stats["tiles"] == 2
    assert sorted(outs) == [0, 1]
    assert handle.done and handle.error is None


def test_run_master_xjob_reenters_after_worker_timeout_requeue(monkeypatch):
    """A worker claims tiles and dies: the requeue lands AFTER the
    master's executor view drained. The master must re-enter the
    executor and finish the tiles locally (the run_master_elastic
    fault-tolerance contract) instead of deadline-breaking with an
    incomplete canvas."""
    from unittest import mock

    from comfyui_distributed_tpu.graph import ExecutionContext
    from comfyui_distributed_tpu.graph import batch_executor as bx
    from comfyui_distributed_tpu.jobs import JobStore
    from comfyui_distributed_tpu.resilience.chaos import (
        _ensure_server_loop,
        _stub_stepwise,
    )
    from comfyui_distributed_tpu.utils import config as config_mod

    bx._reset_shared_executor_for_tests()
    monkeypatch.setenv("CDT_XJOB_BATCH", "1")
    monkeypatch.setenv("CDT_DETERMINISTIC_BLEND", "1")
    store = JobStore()
    real_pull_tasks = store.pull_tasks
    state = {"stolen": False}

    async def stealing_pull_tasks(job_id, worker_id, *args, **kwargs):
        if worker_id == "master" and not state["stolen"]:
            # the dying worker wins the first grant and never submits
            state["stolen"] = True
            await store.pull_task(job_id, "ghost", timeout=0.1)
            await store.pull_task(job_id, "ghost", timeout=0.1)
            return []
        return await real_pull_tasks(job_id, worker_id, *args, **kwargs)

    store.pull_tasks = stealing_pull_tasks
    ctx = ExecutionContext(
        server=types.SimpleNamespace(job_store=store), config={"workers": []}
    )
    bundle = types.SimpleNamespace(params=None)
    image = jnp.asarray(
        np.random.default_rng(0).random((1, 32, 96, 3)), jnp.float32
    )
    pos = neg = jnp.zeros((1, 4, 8), jnp.float32)
    from comfyui_distributed_tpu.graph import usdu_elastic as elastic

    with _ensure_server_loop(), mock.patch(
        "comfyui_distributed_tpu.ops.stepwise.make_stepwise_tile_processor",
        lambda *a, **k: _stub_stepwise(2),
    ), mock.patch.object(
        config_mod, "get_worker_timeout_seconds", lambda path=None: 0.5
    ):
        out = elastic.run_master_elastic(
            bundle, image, pos, neg,
            job_id="xjob-requeue",
            enabled_worker_ids=["ghost"],
            upscale_by=2.0, tile=64, padding=16,
            steps=2, sampler="euler", scheduler="karras",
            cfg=1.0, denoise=0.3, seed=0, context=ctx,
        )
    out = np.asarray(out)
    assert out.shape == (1, 64, 192, 3)
    assert store.tile_jobs == {}  # settled, nothing leaked
    bx._reset_shared_executor_for_tests()
