"""Graph validation + end-to-end workflow execution, including the
mesh-parallel txt2img workflow (the reference's distributed-txt2img
semantics) on the virtual 8-device mesh."""

import numpy as np
import pytest

from comfyui_distributed_tpu.graph import (
    ExecutionContext,
    GraphExecutor,
    validate_prompt,
)
from comfyui_distributed_tpu.parallel import build_mesh
from comfyui_distributed_tpu.utils.exceptions import PromptValidationError


def _txt2img_prompt(seed=42):
    return {
        "1": {"class_type": "CheckpointLoaderSimple", "inputs": {"ckpt_name": "tiny-unet"}},
        "2": {"class_type": "CLIPTextEncode", "inputs": {"text": "a cat", "clip": ["1", 1]}},
        "3": {"class_type": "CLIPTextEncode", "inputs": {"text": "", "clip": ["1", 1]}},
        "4": {"class_type": "EmptyLatentImage", "inputs": {"width": 32, "height": 32, "batch_size": 1}},
        "5": {"class_type": "DistributedSeed", "inputs": {"seed": seed}},
        "6": {
            "class_type": "KSampler",
            "inputs": {
                "model": ["1", 0], "seed": ["5", 0], "steps": 2, "cfg": 3.0,
                "sampler_name": "euler", "scheduler": "karras",
                "positive": ["2", 0], "negative": ["3", 0],
                "latent_image": ["4", 0], "denoise": 1.0,
            },
        },
        "7": {"class_type": "VAEDecode", "inputs": {"samples": ["6", 0], "vae": ["1", 2]}},
        "8": {"class_type": "DistributedCollector", "inputs": {"images": ["7", 0]}},
        "9": {"class_type": "PreviewImage", "inputs": {"images": ["8", 0]}},
    }


def test_validate_rejects_unknown_class():
    with pytest.raises(PromptValidationError) as exc:
        validate_prompt({"1": {"class_type": "NoSuchNode", "inputs": {}}})
    assert "1" in exc.value.node_errors


def test_validate_rejects_missing_link_and_input():
    prompt = {
        "1": {"class_type": "KSampler", "inputs": {"model": ["99", 0]}},
    }
    with pytest.raises(PromptValidationError) as exc:
        validate_prompt(prompt)
    msgs = " ".join(exc.value.node_errors["1"])
    assert "missing node" in msgs
    assert "positive" in msgs  # required input absent with no default


def test_validate_rejects_cycle():
    prompt = {
        "1": {"class_type": "DistributedCollector", "inputs": {"images": ["2", 0]}},
        "2": {"class_type": "DistributedCollector", "inputs": {"images": ["1", 0]}},
    }
    with pytest.raises(PromptValidationError) as exc:
        validate_prompt(prompt)
    assert "cycle" in str(exc.value)


def test_single_participant_execution():
    ctx = ExecutionContext()
    outputs = GraphExecutor(ctx).execute(_txt2img_prompt())
    (result,) = (outputs[k] for k in outputs)
    images = result[0]["images"]
    assert images.shape == (1, 32, 32, 3)


def test_mesh_parallel_execution_collects_all_participants():
    ctx = ExecutionContext(mesh=build_mesh({"data": 8}))
    outputs = GraphExecutor(ctx).execute(_txt2img_prompt())
    images = np.asarray(list(outputs.values())[0][0]["images"])
    assert images.shape == (8, 32, 32, 3)
    assert len({images[i].tobytes() for i in range(8)}) == 8


def test_mesh_parallel_deterministic():
    ctx = ExecutionContext(mesh=build_mesh({"data": 8}))
    a = np.asarray(list(GraphExecutor(ctx).execute(_txt2img_prompt())
                        .values())[0][0]["images"])
    ctx2 = ExecutionContext(mesh=build_mesh({"data": 8}))
    b = np.asarray(list(GraphExecutor(ctx2).execute(_txt2img_prompt())
                        .values())[0][0]["images"])
    np.testing.assert_array_equal(a, b)


def test_batch_divider_in_graph():
    prompt = {
        "1": {"class_type": "EmptyLatentImage", "inputs": {"width": 32, "height": 32, "batch_size": 1}},
        "2": {"class_type": "DistributedEmptyImage", "inputs": {}},
        "3": {"class_type": "ImageBatchDivider", "inputs": {"images": ["2", 0], "divide_by": 3}},
        "4": {"class_type": "PreviewImage", "inputs": {"images": ["3", 0]}},
    }
    outputs = GraphExecutor(ExecutionContext()).execute(prompt)
    assert list(outputs.values())[0][0]["images"].shape[0] == 0


def test_node_cache_evicts_absent_node_ids():
    """Cross-run cache entries for node ids not in the current prompt
    are pruned (long-lived servers must not accumulate stale tensors)."""
    from comfyui_distributed_tpu.graph.executor import ExecutionContext, GraphExecutor

    ctx = ExecutionContext()
    ex = GraphExecutor(ctx)
    p1 = {
        "1": {"class_type": "DistributedEmptyImage", "inputs": {}},
        "2": {"class_type": "ImageScale",
              "inputs": {"image": ["1", 0], "upscale_method": "nearest",
                          "width": 4, "height": 4}},
    }
    ex.execute(p1)
    cache = ctx.extras["node_cache"]
    assert set(cache) <= {"1", "2"} and cache
    p2 = {
        "9": {"class_type": "DistributedEmptyImage", "inputs": {}},
    }
    ex.execute(p2)
    assert "2" not in ctx.extras["node_cache"]
    assert set(ctx.extras["node_cache"]) <= {"9"}
