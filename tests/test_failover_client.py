"""Worker-client HA units: master address rotation, epoch learning,
and the heartbeat failure backoff (the satellite fixing the
full-rate debug_log/retry flood during a master outage)."""

import time

import pytest

from comfyui_distributed_tpu.graph.usdu_elastic import (
    HEARTBEAT_BACKOFF_BASE_SECONDS,
    HTTPWorkClient,
    parse_master_urls,
)
from comfyui_distributed_tpu.telemetry.metrics import (
    get_metrics_registry,
    reset_metrics_registry,
)
from comfyui_distributed_tpu.utils import constants
from comfyui_distributed_tpu.utils.async_helpers import ServerLoopThread


@pytest.fixture()
def loop_thread():
    thread = ServerLoopThread()
    thread.start()
    yield thread
    thread.stop()


def test_parse_master_urls_splits_and_strips():
    assert parse_master_urls("http://a:1, http://b:2/,") == [
        "http://a:1", "http://b:2",
    ]
    assert parse_master_urls(["http://a:1/"]) == ["http://a:1"]


def test_consecutive_errors_rotate_to_next_master(monkeypatch):
    reset_metrics_registry()
    monkeypatch.setattr(constants, "FAILOVER_AFTER_ERRORS", 2)
    client = HTTPWorkClient("http://a:1,http://b:2", "j", "w1")
    assert client.master_url == "http://a:1"
    client._count_error("pull")
    assert client.master_url == "http://a:1"  # one failure is a blip
    client._count_error("pull")
    assert client.master_url == "http://b:2"  # threshold: re-point
    assert client.failovers == 1
    # errors were counted per op, and the re-point as a worker failover
    rendered = get_metrics_registry().render()
    assert 'cdt_worker_master_errors_total{op="pull"} 2' in rendered
    assert 'cdt_failover_total{role="worker"} 1' in rendered
    # rotation wraps: two more failures point back at the first master
    client._count_error("submit")
    client._count_error("submit")
    assert client.master_url == "http://a:1"


def test_single_master_never_rotates(monkeypatch):
    reset_metrics_registry()
    monkeypatch.setattr(constants, "FAILOVER_AFTER_ERRORS", 1)
    client = HTTPWorkClient("http://a:1", "j", "w1")
    for _ in range(5):
        client._count_error("heartbeat")
    assert client.master_url == "http://a:1"
    assert client.failovers == 0


def test_learn_epoch_is_monotonic_and_ignores_garbage():
    client = HTTPWorkClient("http://a:1", "j", "w1")
    assert client.epoch is None
    client._learn_epoch(2)
    assert client.epoch == 2
    client._learn_epoch(1)     # older: ignored
    client._learn_epoch(None)  # absent: ignored
    client._learn_epoch("x")   # garbage: ignored
    assert client.epoch == 2
    client._learn_epoch("3")   # takeover: adopted
    assert client.epoch == 3


def test_heartbeat_backoff_suppresses_the_failure_flood(loop_thread):
    """Consecutive heartbeat failures must back off exponentially: the
    2nd..kth beats inside the suppression window never leave the
    process, so a dead master sees (and the log records) one attempt
    per window instead of one per tile."""
    client = HTTPWorkClient("http://a:1", "j", "w1")
    calls = []

    async def failing_post(path, payload, op="transport"):
        calls.append(op)
        raise OSError("connection refused")

    client._post = failing_post
    client.heartbeat()
    assert calls == ["heartbeat"]
    assert client._hb_failures == 1
    window = client._hb_suppressed_until - time.monotonic()
    assert 0 < window <= HEARTBEAT_BACKOFF_BASE_SECONDS
    # inside the window: suppressed, no RPC attempted
    client.heartbeat()
    client.heartbeat()
    assert calls == ["heartbeat"]
    # window elapsed: exactly one more attempt, and the backoff doubles
    client._hb_suppressed_until = 0.0
    client.heartbeat()
    assert calls == ["heartbeat", "heartbeat"]
    assert client._hb_failures == 2
    second_window = client._hb_suppressed_until - time.monotonic()
    assert second_window > window

    # a success resets the schedule completely
    async def ok_post(path, payload, op="transport"):
        calls.append("ok")
        return {"status": "ok"}

    client._post = ok_post
    client._hb_suppressed_until = 0.0
    client.heartbeat()
    assert client._hb_failures == 0
    assert client._hb_suppressed_until == 0.0
