"""Two-tier ring-buffer time series (telemetry/timeseries.py): bucket
aggregation, raw→rollup fallback, counter-delta semantics, the
cardinality cap, and the departed-worker eviction seam."""

import pytest

from comfyui_distributed_tpu.telemetry.timeseries import SeriesStore

pytestmark = pytest.mark.fast


class Clock:
    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


@pytest.fixture()
def clock():
    return Clock()


def make_store(clock, **kwargs):
    kwargs.setdefault("raw_step", 10.0)
    kwargs.setdefault("raw_points", 6)
    kwargs.setdefault("rollup_step", 60.0)
    kwargs.setdefault("rollup_points", 4)
    return SeriesStore(clock=clock, **kwargs)


def test_bucket_aggregates_min_max_sum_count_last(clock):
    store = make_store(clock)
    for value in (3.0, 1.0, 2.0):
        store.record("g", value)
    points = store.window("g", 100.0)
    assert len(points) == 1
    b = points[0]
    assert (b["min"], b["max"], b["sum"], b["count"], b["last"]) == (
        1.0, 3.0, 6.0, 3, 2.0
    )


def test_window_served_from_raw_then_rollup(clock):
    store = make_store(clock)
    # raw tier holds 6 x 10s buckets; fill 10 buckets so the oldest 4
    # survive only in the 60s rollups
    for i in range(10):
        store.record("x", float(i))
        clock.advance(10.0)
    recent = store.window("x", 50.0)
    assert all(p["count"] == 1 for p in recent)  # raw resolution
    deep = store.window("x", 10 * 10.0 + 5)
    # raw can't reach back 105s -> rollup tier (60s buckets, count>1)
    assert any(p["count"] > 1 for p in deep)


def test_counter_delta_over_window(clock):
    store = make_store(clock)
    total = 0.0
    for _ in range(6):
        total += 5.0
        store.record("c", total)
        clock.advance(10.0)
    # last 30s: buckets at t-30..t-10 -> 2..3 increments of 5
    assert store.delta("c", 30.0) in (10.0, 15.0)
    # window longer than history: full delta minus the base bucket
    assert store.delta("c", 10_000.0) == total - 5.0
    assert store.delta("unknown", 30.0) == 0.0


def test_delta_never_uses_a_rollup_bucket_overlapping_raw(clock):
    """The burn-rate regression: with history shorter than the window,
    the single rollup bucket CONTAINS `now` — using its `last` as the
    window base would zero every delta."""
    store = make_store(clock)
    store.record("c", 1.0)
    clock.advance(15.0)  # next raw bucket, same 60s rollup bucket
    store.record("c", 11.0)
    clock.advance(11.0)
    assert store.delta("c", 1_000.0) == 10.0


def test_series_cap_rejects_new_label_sets(clock):
    store = make_store(clock, max_series=3)
    for i in range(5):
        store.record("s", 1.0, worker_id=f"w{i}")
    assert store.series_count() == 3
    assert store.overflows == 2
    # established series keep recording
    assert store.record("s", 2.0, worker_id="w0") is True
    assert store.record("s", 2.0, worker_id="w99") is False


def test_evict_label_drops_every_series_for_the_worker(clock):
    store = make_store(clock)
    store.record("a", 1.0, worker_id="w1")
    store.record("b", 1.0, worker_id="w1")
    store.record("a", 1.0, worker_id="w2")
    assert store.evict_label("worker_id", "w1") == 2
    assert store.series_count() == 1
    assert store.label_values("a", "worker_id") == ["w2"]


def test_label_order_never_splits_a_series(clock):
    store = make_store(clock)
    store.record("m", 1.0, a="1", b="2")
    store.record("m", 2.0, b="2", a="1")
    assert store.series_count() == 1
    assert store.latest("m", a="1", b="2") == 2.0


def test_backwards_clock_folds_into_newest_bucket(clock):
    store = make_store(clock)
    store.record("g", 1.0)
    store.record("g", 2.0, ts=clock() - 50.0)  # stale timestamp
    points = store.window("g", 100.0)
    assert len(points) == 1 and points[0]["count"] == 2
