"""Metrics registry: instrument semantics, Prometheus exposition
validity, and the cdt_ naming conventions over the canonical
instrument set (telemetry/instruments.py)."""

import inspect
import re
import threading

import pytest

from comfyui_distributed_tpu.telemetry import (
    get_metrics_registry,
    reset_metrics_registry,
)
from comfyui_distributed_tpu.telemetry import instruments
from comfyui_distributed_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


@pytest.fixture()
def registry():
    return MetricsRegistry()


# --- counters -------------------------------------------------------------

def test_counter_inc_and_labels(registry):
    c = registry.counter("cdt_x_total", "help", ("worker_id",))
    c.inc(worker_id="w1")
    c.inc(2, worker_id="w1")
    c.inc(worker_id="w2")
    assert c.value(worker_id="w1") == 3
    assert c.value(worker_id="w2") == 1


def test_counter_rejects_negative_and_bad_labels(registry):
    c = registry.counter("cdt_x_total", "help", ("a",))
    with pytest.raises(ValueError):
        c.inc(-1, a="x")
    with pytest.raises(ValueError):
        c.inc(b="x")  # wrong label name
    with pytest.raises(ValueError):
        c.inc()  # missing label


def test_get_or_create_is_idempotent_but_type_safe(registry):
    c1 = registry.counter("cdt_x_total", "help", ("a",))
    c2 = registry.counter("cdt_x_total", "help", ("a",))
    assert c1 is c2
    with pytest.raises(ValueError):
        registry.gauge("cdt_x_total", "help", ("a",))
    with pytest.raises(ValueError):
        registry.counter("cdt_x_total", "help", ("b",))


# --- gauges ---------------------------------------------------------------

def test_gauge_set_inc_dec(registry):
    g = registry.gauge("cdt_depth", "help")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4


# --- histograms -----------------------------------------------------------

def test_histogram_buckets_cumulative(registry):
    h = registry.histogram("cdt_lat_seconds", "help", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    text = registry.render()
    assert 'cdt_lat_seconds_bucket{le="0.1"} 1' in text
    assert 'cdt_lat_seconds_bucket{le="1"} 3' in text
    assert 'cdt_lat_seconds_bucket{le="10"} 4' in text
    assert 'cdt_lat_seconds_bucket{le="+Inf"} 5' in text
    assert "cdt_lat_seconds_count 5" in text
    assert h.count() == 5


# --- exposition -----------------------------------------------------------

def test_render_format_and_escaping(registry):
    c = registry.counter("cdt_esc_total", "has \"quotes\"", ("name",))
    c.inc(name='va"l\nue\\x')
    text = registry.render()
    lines = text.strip().splitlines()
    assert "# HELP cdt_esc_total" in lines[0]
    assert lines[1] == "# TYPE cdt_esc_total counter"
    assert lines[2] == 'cdt_esc_total{name="va\\"l\\nue\\\\x"} 1'
    assert text.endswith("\n")


def test_collectors_run_at_scrape_and_errors_are_contained(registry):
    g = registry.gauge("cdt_live", "help")
    calls = []

    def good():
        calls.append(1)
        g.set(len(calls))

    def broken():
        raise RuntimeError("boom")

    unregister = registry.register_collector(good)
    registry.register_collector(broken)
    text = registry.render()
    assert "cdt_live 1" in text
    text = registry.render()
    assert "cdt_live 2" in text
    unregister()
    registry.render()
    assert len(calls) == 2


def test_thread_safety_under_contention(registry):
    c = registry.counter("cdt_contended_total", "help")

    def bump():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 8000


# --- cardinality caps ------------------------------------------------------

def test_label_churn_is_bounded_by_the_series_cap(registry):
    """A worker-id churn storm cannot grow a metric (and the scrape)
    without bound: series beyond the cap collapse into `_overflow` and
    the registry's warning counter records every collapsed write."""
    c = registry.counter("cdt_churn_total", "help", ("worker_id",))
    c.max_series = 10
    for i in range(1000):
        c.inc(worker_id=f"w{i}")
    with c._lock:
        assert len(c._values) == 11  # 10 real series + _overflow
    assert c.value(worker_id="_overflow") == 990
    # established series keep counting normally after the cap is hit
    c.inc(worker_id="w3")
    assert c.value(worker_id="w3") == 2
    overflow = registry.get(MetricsRegistry.OVERFLOW_COUNTER_NAME)
    assert overflow.value(metric="cdt_churn_total") == 990
    text = registry.render()
    assert 'cdt_churn_total{worker_id="_overflow"} 990' in text


def test_histogram_and_gauge_series_are_capped_too(registry):
    h = registry.histogram("cdt_cap_seconds", "help", ("worker_id",), buckets=(1.0,))
    h.max_series = 3
    g = registry.gauge("cdt_cap_depth", "help", ("worker_id",))
    g.max_series = 3
    for i in range(20):
        h.observe(0.5, worker_id=f"w{i}")
        g.set(i, worker_id=f"w{i}")
    with h._lock:
        assert len(h._series) == 4
    with g._lock:
        assert len(g._values) == 4
    assert h.count(worker_id="_overflow") == 17
    overflow = registry.get(MetricsRegistry.OVERFLOW_COUNTER_NAME)
    assert overflow.value(metric="cdt_cap_seconds") == 17
    assert overflow.value(metric="cdt_cap_depth") == 17


def test_unlabelled_metrics_are_never_capped(registry):
    c = registry.counter("cdt_plain_total", "help")
    c.max_series = 1
    for _ in range(5):
        c.inc()
    assert c.value() == 5


def test_series_cap_env_override(monkeypatch):
    monkeypatch.setenv("CDT_METRIC_MAX_SERIES", "7")
    registry = MetricsRegistry()
    c = registry.counter("cdt_env_cap_total", "help", ("worker_id",))
    assert c.max_series == 7


# --- global registry ------------------------------------------------------

def test_global_registry_reset():
    r1 = get_metrics_registry()
    assert get_metrics_registry() is r1
    reset_metrics_registry()
    assert get_metrics_registry() is not r1


# --- naming conventions over the canonical instrument set -----------------

_NAME_CONVENTION = re.compile(r"^cdt_[a-z0-9_]+$")
_LABEL_CONVENTION = re.compile(r"^[a-z][a-z0-9_]*$")


def _instrument_accessors():
    for name, fn in inspect.getmembers(instruments, inspect.isfunction):
        if (
            name.startswith("_")
            or name == "bind_server_collectors"
            or fn.__module__ != instruments.__name__
        ):
            continue
        sig = inspect.signature(fn)
        if len(sig.parameters) == 0:
            yield name, fn


def test_every_instrument_follows_naming_conventions():
    found = []
    for accessor_name, fn in _instrument_accessors():
        metric = fn()
        found.append(metric.name)
        assert _NAME_CONVENTION.match(metric.name), (accessor_name, metric.name)
        for label in metric.labelnames:
            assert _LABEL_CONVENTION.match(label), (metric.name, label)
        if isinstance(metric, Counter):
            assert metric.name.endswith("_total"), metric.name
        if isinstance(metric, Histogram):
            assert metric.name.endswith("_seconds"), metric.name
        if isinstance(metric, Gauge):
            assert not metric.name.endswith("_total"), metric.name
        assert metric.help, f"{metric.name} needs help text"
    # the canonical set actually covers the instrumented layers
    assert "cdt_store_pulls_total" in found
    assert "cdt_tile_stage_seconds" in found
    assert "cdt_worker_breaker_state" in found
    assert "cdt_retries_total" in found
