"""Region-mode chaos: quorum-lease failover (no shared filesystem
arbitrating) and the two-shard region run with one shard dying mid-job.

The acceptance bundle this file proves:

- a shard master SIGKILL'd mid-job fails over through the quorum lease
  and the canvas is BIT-IDENTICAL to the fault-free run;
- a lease peer crashing mid-acquire (both halves: write lost, ack
  lost) still elects exactly one new master and changes nothing else;
- the fenced zombie's stale submit journals NOTHING;
- the other shard's job — open across the whole outage — loses zero
  tiles, keeps its own epoch, and the consistent-hash placement map
  never moves;
- the autoscaler's decision ledger spans the outage with measured
  chip-second demand/capacity windows and a settled cost line.
"""

import numpy as np
import pytest

from comfyui_distributed_tpu.resilience.chaos import (
    run_chaos_quorum_failover,
    run_chaos_region,
    run_chaos_usdu,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def baseline():
    result = run_chaos_usdu(seed=11)
    return result.output


def _assert_quorum_failover_invariants(baseline, result):
    assert "crash" in result.fired_kinds()
    assert result.epochs[1] > result.epochs[0]
    assert result.zombie_fenced, "ex-active journal append was not fenced"
    assert result.stale_pull_rejected
    assert result.stale_submit_rejected
    assert result.zombie_journaled_records == 0
    assert result.report["jobs_recovered"] == 1
    np.testing.assert_array_equal(baseline, result.output)


def test_quorum_failover_master_sigkill_bit_identical(baseline, tmp_path):
    """The region acceptance scenario: the shard master dies mid-job
    with the lease arbitrated by a majority of off-node peer registers
    — no flock, no shared lease file — and everything downstream of
    the epoch (fencing, StaleEpoch, the canvas) behaves exactly as the
    file-lease failover does."""
    result = run_chaos_quorum_failover(
        seed=11,
        crash_plan="crash@store:pull:master#2",
        journal_dir=str(tmp_path / "wal"),
    )
    _assert_quorum_failover_invariants(baseline, result)
    # unforced takeover of an expired quorum lease: exactly epoch+1
    assert result.epochs == (1, 2)


@pytest.mark.parametrize("mode", ["before", "after"])
def test_quorum_failover_survives_peer_crash_mid_acquire(
    baseline, tmp_path, mode
):
    """One lease peer crashes in the middle of the standby's acquire —
    before applying the proposal (the write is lost) or after (the ack
    is lost). A majority of the survivors still elects, the epoch
    stays monotonic, and the canvas stays bit-identical."""
    result = run_chaos_quorum_failover(
        seed=11,
        crash_plan="crash@store:pull:master#2",
        journal_dir=str(tmp_path / "wal"),
        peer_crash=mode,
    )
    _assert_quorum_failover_invariants(baseline, result)


def test_region_shard_failover_leaves_other_shard_untouched(
    baseline, tmp_path
):
    """Two shards, one region: shard0's master is killed mid-job and
    fails over through the quorum lease; shard1's job — opened before
    the crash, finished after — completes with zero tile loss on its
    own epoch, and the ring's placement map is identical before and
    after (membership never changed)."""
    result = run_chaos_region(
        seed=11, journal_root=str(tmp_path / "region")
    )
    # the failed shard recovered bit-identically, fully fenced
    _assert_quorum_failover_invariants(baseline, result.shard0)
    # zero cross-shard loss: the untouched shard kept every tile
    assert result.shard1_tiles_completed == 4
    assert result.shard1_epoch == 1
    assert result.shard1_journal_appends > 0
    # coordination-free placement: no key moved
    assert result.placement_drift == 0
    assert set(result.placements.values()) == {"shard0", "shard1"}


def test_region_autoscaler_records_measured_decisions(tmp_path):
    """The autoscaler's ledger across the outage: the burn alert
    during the crash forces a scale_up carrying the chip-second
    demand/capacity window that justified it, and the next evaluation
    settles the decision with the measured capacity delta it bought."""
    result = run_chaos_region(
        seed=11, journal_root=str(tmp_path / "region")
    )
    decisions = result.autoscale_decisions
    assert len(decisions) >= 3
    ups = [d for d in decisions if d["action"] == "scale_up"]
    assert ups, f"no scale_up in {[d['action'] for d in decisions]}"
    up = ups[0]
    assert up["reason"].startswith("burn:")
    assert up["demand_chip_s"] > 0
    assert up["capacity_chip_s"] > 0
    # settled one window later: the measured cost/benefit line
    assert up["measured"] is not None
    assert up["measured"]["capacity_delta_chip_s"] != 0
    assert "utilization_after" in up["measured"]
