"""Request-lifecycle armor at the JobStore seam: cooperative
cancellation (refund accounting, terminal drops), end-to-end deadlines
(lazy expiry + sweep), poison-tile quarantine (attempt budget, pardon
hook, degraded completion accounting), and the journal/replica parity
of the new record types (cancel, tile_quarantine, deadline on
job_init)."""

import asyncio

import pytest

from comfyui_distributed_tpu.durability import state as dstate
from comfyui_distributed_tpu.jobs import JobStore


def run(coro):
    return asyncio.run(coro)


# --------------------------------------------------------------------------
# cooperative cancellation
# --------------------------------------------------------------------------


def test_cancel_refunds_pending_and_in_flight():
    async def body():
        store = JobStore()
        await store.init_tile_job("j", list(range(8)))
        for wid in ("w1", "w2"):
            assert await store.pull_task("j", wid) is not None
        acct = await store.cancel_job("j", reason="client")
        assert acct["pending_refunded"] == 6
        assert acct["in_flight_refunded"] == 2
        assert acct["workers"] == ["w1", "w2"]
        stats = await store.stats()
        assert stats["queue_depth"] == 0
        assert stats["in_flight"] == 0

    run(body())


def test_cancel_is_idempotent_and_terminal():
    async def body():
        store = JobStore()
        await store.init_tile_job("j", [0, 1])
        t = await store.pull_task("j", "w1")
        first = await store.cancel_job("j")
        assert not first["already_cancelled"]
        again = await store.cancel_job("j")
        assert again["already_cancelled"]
        # terminal: pulls read drained, submits drop, releases no-op
        assert await store.pull_task("j", "w1") is None
        assert not await store.submit_result("j", "w1", t, None)
        assert await store.release_tasks("j", "w1", [t]) == []
        job = await store.get_tile_job("j")
        assert t not in job.completed

    run(body())


def test_cancel_unknown_job_returns_none():
    async def body():
        store = JobStore()
        assert await store.cancel_job("nope") is None

    run(body())


def test_cancel_record_is_journaled_before_ack():
    async def body():
        records = []
        store = JobStore()
        store.journal_sink = records.append
        await store.init_tile_job("j", [0, 1, 2])
        await store.pull_task("j", "w1")
        await store.cancel_job("j", reason="deadline")
        kinds = [r["type"] for r in records]
        assert kinds == ["job_init", "pull", "cancel"]
        assert records[-1] == {"type": "cancel", "job": "j", "reason": "deadline"}

    run(body())


# --------------------------------------------------------------------------
# deadlines
# --------------------------------------------------------------------------


def test_deadline_expires_lazily_on_pull():
    async def body():
        store = JobStore()
        await store.init_tile_job("j", [0, 1], deadline_s=0.01)
        await asyncio.sleep(0.03)
        assert await store.pull_task("j", "w1") is None
        job = await store.get_tile_job("j")
        assert job.cancelled and job.cancel_reason == "deadline"

    run(body())


def test_deadline_sweep_expires_only_overdue_jobs():
    async def body():
        store = JobStore()
        await store.init_tile_job("overdue", [0], deadline_s=0.01)
        await store.init_tile_job("fine", [0], deadline_s=60.0)
        await store.init_tile_job("none", [0])
        await asyncio.sleep(0.03)
        expired = await store.sweep_deadlines()
        assert expired == ["overdue"]
        assert not (await store.get_tile_job("fine")).cancelled
        assert not (await store.get_tile_job("none")).cancelled
        # a second sweep is a no-op (already terminal)
        assert await store.sweep_deadlines() == []

    run(body())


def test_note_job_deadline_arms_later_init():
    async def body():
        store = JobStore()
        store.note_job_deadline("j", 45.0)
        store.note_job_deadline("bogus", "not-a-number")  # ignored
        job = await store.init_tile_job("j", [0, 1])
        assert job.deadline_s == 45.0
        assert job.deadline_remaining() is not None
        # consumed: a later unrelated job does not inherit it
        other = await store.init_tile_job("k", [0])
        assert other.deadline_s is None

    run(body())


def test_job_init_journal_record_carries_deadline():
    async def body():
        records = []
        store = JobStore()
        store.journal_sink = records.append
        await store.init_tile_job("j", [0], deadline_s=30.0)
        assert records[0]["deadline_s"] == 30.0

    run(body())


# --------------------------------------------------------------------------
# poison-tile quarantine
# --------------------------------------------------------------------------


def _crash_worker(store, job_id, wid):
    """Pull one tile as `wid` then simulate its death (quarantine-path
    requeue, the same seam the circuit breaker uses)."""

    async def body():
        tid = await store.pull_task(job_id, wid)
        await store.requeue_worker_tasks(wid, job_id)
        return tid

    return run(body())


def test_tile_quarantined_after_max_attempts_and_victims_pardoned():
    store = JobStore(max_attempts=2)
    pardoned = []
    store.poison_pardon = pardoned.extend
    run(store.init_tile_job("p", [0]))
    _crash_worker(store, "p", "a")
    _crash_worker(store, "p", "b")
    job = run(store.get_tile_job("p"))
    assert job.quarantined_tiles == {0}
    assert job.attempts[0] == 2
    assert pardoned == ["a", "b"]
    # quarantined = settled: the job is complete (degraded)
    assert run(store.is_complete("p"))
    assert run(store.pull_task("p", "c")) is None  # nothing left to pull


def test_released_tiles_do_not_charge_the_poison_budget():
    async def body():
        store = JobStore(max_attempts=1)
        await store.init_tile_job("p", [0])
        for wid in ("a", "b", "c"):
            tid = await store.pull_task("p", wid)
            assert tid == 0
            # voluntary hand-back (graceful drain): NOT an attempt
            assert await store.release_tasks("p", wid, [0]) == [0]
        job = await store.get_tile_job("p")
        assert job.quarantined_tiles == set()
        assert job.attempts == {}

    run(body())


def test_late_completion_settles_a_quarantined_tile_once():
    async def body():
        store = JobStore(max_attempts=1)
        await store.init_tile_job("p", [0])
        tid = await store.pull_task("p", "a")
        # speculated copy claimed by b BEFORE a's death poisons the tile
        await store.speculate_in_flight("p")
        b_tid = await store.pull_task("p", "b")
        assert b_tid == tid
        await store.requeue_worker_tasks("a", "p")
        job = await store.get_tile_job("p")
        assert job.quarantined_tiles == {0}
        # b's late result still lands: first real completion wins and
        # the quarantine is dropped so the tile counts exactly once
        assert await store.submit_result("p", "b", 0, None)
        assert job.quarantined_tiles == set()
        assert await store.is_complete("p")

    run(body())


def test_quarantine_journal_records_replay_to_same_state():
    records = []
    store = JobStore(max_attempts=2)
    store.journal_sink = records.append
    run(store.init_tile_job("p", [0]))
    _crash_worker(store, "p", "a")  # pulls 0, dies
    _crash_worker(store, "p", "b")  # pulls the requeued 0, dies
    kinds = [r["type"] for r in records]
    assert kinds.count("tile_quarantine") == 1
    state = dstate.new_state()
    for record in records:
        dstate.apply_record(state, record)
    job = state["jobs"]["p"]
    assert job["quarantined"] == [0]
    assert job["attempts"] == {"0": 2}
    assert 0 not in job["pending"]
    # prepare_for_restart keeps the quarantine settled (no re-run)
    stats = dstate.prepare_for_restart(state)
    assert 0 not in state["jobs"]["p"]["pending"]
    materialized = dstate.materialize(state)["p"]
    assert materialized.quarantined_tiles == {0}
    assert materialized.attempts == {0: 2}
    assert stats["jobs_cancelled"] == 0


# --------------------------------------------------------------------------
# crash-after-cancel recovery + replica parity
# --------------------------------------------------------------------------


def test_crash_after_cancel_recovers_to_the_same_terminal_state(tmp_path):
    from comfyui_distributed_tpu.durability import (
        DurabilityManager,
        StandbyReplica,
    )

    journal_dir = str(tmp_path / "wal")

    async def phase1():
        store = JobStore()
        manager = DurabilityManager(journal_dir, fsync_every=0)
        store.journal_sink = manager.record
        sub = manager.subscribe_replica()
        replica = StandbyReplica()
        replica.reset(sub.snapshot_state, sub.head_lsn, sub.epoch)
        await store.init_tile_job("j", [0, 1, 2], deadline_s=60.0)
        await store.pull_task("j", "w1")
        await store.cancel_job("j", reason="client")
        # "crash": the store is abandoned before any cleanup record
        for record in sub.pop(max_items=10000):
            replica.apply(record)
        manager.close()
        return replica

    replica = run(phase1())
    # the replica applied the cancel: terminal drained state
    rjob = replica._state["jobs"]["j"]
    assert rjob["cancelled"] and rjob["pending"] == [] and rjob["assigned"] == {}

    async def phase2():
        store = JobStore()
        manager = DurabilityManager(journal_dir, fsync_every=0)
        report = manager.recover(store)
        manager.close()
        return store, report

    store2, report = run(phase2())
    # the cancelled job is NOT resurrected (nothing requeued from it)
    assert "j" not in store2.tile_jobs
    assert report.jobs_cancelled == 1
    assert report.tasks_requeued == 0


def test_recovered_job_rearms_its_deadline(tmp_path):
    from comfyui_distributed_tpu.durability import DurabilityManager

    journal_dir = str(tmp_path / "wal")

    async def phase1():
        store = JobStore()
        manager = DurabilityManager(journal_dir, fsync_every=0)
        store.journal_sink = manager.record
        await store.init_tile_job("j", [0, 1], deadline_s=90.0)
        manager.close()

    run(phase1())

    async def phase2():
        store = JobStore()
        manager = DurabilityManager(journal_dir, fsync_every=0)
        manager.recover(store)
        manager.close()
        return store

    store2 = run(phase2())
    job = store2.tile_jobs["j"]
    assert job.deadline_s == 90.0
    assert job.deadline_at is not None
    remaining = job.deadline_remaining()
    assert remaining is not None and 80.0 < remaining <= 90.0
