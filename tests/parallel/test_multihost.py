"""Real 2-process multihost: two CPU processes join one JAX runtime
over localhost DCN via parallel/multihost.maybe_init_multihost, the
global device count spans both, and a cross-process psum produces the
correct value on each host (VERDICT round-1 next-step 10)."""

import os
import socket
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["CDT_TEST_REPO"])
from comfyui_distributed_tpu.parallel.multihost import maybe_init_multihost, is_multihost
from comfyui_distributed_tpu.parallel.mesh import shard_map_compat

assert maybe_init_multihost() is True
assert is_multihost() is True
pid = jax.process_index()
# 2 processes x 2 local devices = 4 global devices
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()
assert jax.local_device_count() == 2, jax.local_device_count()

# one cross-process collective: psum over the global data axis
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(jax.devices(), ("data",))
local = jnp.arange(2, dtype=jnp.float32) + 10.0 * pid  # distinct per host

def f(x):
    return jax.lax.psum(x, "data")

arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")), local, (4,)
)
out = jax.jit(
    shard_map_compat(f, mesh=mesh, in_specs=P("data"), out_specs=P())
)(arr)
# global shards: [0, 1] (pid 0) + [10, 11] (pid 1) -> psum = 22
assert float(out[0]) == 22.0, out
print(f"MULTIHOST_OK pid={pid}")
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_dcn_collective():
    # bounded by the communicate(timeout=500) below
    port = _free_port()
    env_base = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    procs = []
    for pid in range(2):
        env = dict(env_base)
        env["CDT_TEST_REPO"] = REPO_ROOT
        env["CDT_COORDINATOR"] = f"127.0.0.1:{port}"
        env["CDT_NUM_PROCESSES"] = "2"
        env["CDT_PROCESS_ID"] = str(pid)
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["JAX_PLATFORMS"] = "cpu"
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _CHILD],
                env=env, cwd=REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    outs = []
    for proc in procs:
        try:
            out, err = proc.communicate(timeout=500)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            pytest.fail("multihost processes timed out")
        outs.append((proc.returncode, out, err))
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        assert f"MULTIHOST_OK pid={pid}" in out
