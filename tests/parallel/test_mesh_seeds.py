"""Mesh construction, seed folding, and the collective collector on a
virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.parallel import collective, mesh as meshmod, seeds
from comfyui_distributed_tpu.parallel.mesh import shard_map_compat
from comfyui_distributed_tpu.utils.exceptions import MeshError


def test_eight_virtual_devices():
    assert jax.device_count() == 8


def test_build_default_mesh():
    m = meshmod.build_mesh()
    assert meshmod.data_axis_size(m) == 8
    assert m.shape[meshmod.MODEL_AXIS] == 1


def test_mesh_spec_infer_and_errors():
    m = meshmod.build_mesh({"data": 2, "model": -1})
    assert m.shape["model"] == 4
    with pytest.raises(MeshError):
        meshmod.MeshSpec({"data": 3, "model": -1}).resolve(8)
    with pytest.raises(MeshError):
        meshmod.MeshSpec({"data": -1, "model": -1}).resolve(8)
    with pytest.raises(MeshError):
        meshmod.MeshSpec({"data": 5}).resolve(8)


def test_offset_seed_reference_parity():
    # master (index 0) keeps the base seed; worker i gets base + i + 1
    # matching reference nodes/utilities.py:52-75 where worker_index is
    # 0-based and the node adds (index + 1).
    assert seeds.offset_seed(100, 0) == 100
    assert seeds.offset_seed(100, 1) == 101
    assert seeds.offset_seed(100, 3) == 103
    assert seeds.offset_seed(seeds.MAX_SEED, 1) == 0


def test_participant_keys_distinct_and_deterministic():
    key = jax.random.key(42)
    ks = seeds.participant_keys(key, 8)
    raw = np.asarray(jax.random.key_data(ks))
    assert raw.shape[0] == 8
    assert len({tuple(r) for r in raw}) == 8
    ks2 = seeds.participant_keys(jax.random.key(42), 8)
    np.testing.assert_array_equal(raw, np.asarray(jax.random.key_data(ks2)))


def test_shard_map_collector_gathers_in_participant_order():
    m = meshmod.build_mesh({"data": 8})

    def per_chip(_):
        idx = jax.lax.axis_index(meshmod.DATA_AXIS)
        mine = jnp.full((1, 4), idx, dtype=jnp.float32)
        # The collector: every chip contributes its batch, the gathered
        # result is replicated (out_specs=P()) in participant order.
        return collective.all_gather_batch(mine)

    out = jax.jit(
        shard_map_compat(
            per_chip,
            mesh=m,
            in_specs=jax.sharding.PartitionSpec(),
            out_specs=jax.sharding.PartitionSpec(),
            check=False,
        )
    )(jnp.zeros((1,)))
    gathered = collective.host_collect(out)
    assert gathered.shape == (8, 4)
    np.testing.assert_array_equal(gathered[:, 0], np.arange(8, dtype=np.float32))


def test_reorder_participant_first():
    batches = {3: "w3", 0: "master", 7: "w7", 1: "w1"}
    ordered = collective.reorder_participant_first(batches, enabled_order=[1, 3])
    assert ordered == ["master", "w1", "w3", "w7"]


def test_fsdp_specs():
    from comfyui_distributed_tpu.parallel import sharding

    m = meshmod.build_mesh({"data": 2, "model": 4})
    spec = sharding.fsdp_spec_for((128, 256), 4)
    assert spec == jax.sharding.PartitionSpec(None, "model")
    assert sharding.fsdp_spec_for((3,), 4) == jax.sharding.PartitionSpec()
    params = {"w": np.ones((16, 8), np.float32), "b": np.ones((3,), np.float32)}
    placed = sharding.shard_params(params, m)
    assert placed["w"].sharding.spec == jax.sharding.PartitionSpec("model", None)
    total = collective.host_collect(placed["w"]).sum()
    assert total == 16 * 8
