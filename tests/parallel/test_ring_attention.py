"""Ring attention + context-parallel DiT forward must be numerically
identical to the single-device computation."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.ops.ring_attention import ring_attention
from comfyui_distributed_tpu.parallel import build_mesh
from comfyui_distributed_tpu.parallel.mesh import shard_map_compat
from comfyui_distributed_tpu.parallel.collective import host_collect
from comfyui_distributed_tpu.parallel.sequence import video_forward_context_parallel


def test_ring_attention_matches_full():
    mesh = build_mesh({"data": 8})
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (2, 64, 2, 16)  # global [B, N, H, D], N sharded 8 ways
    q = jax.random.normal(kq, shape, jnp.float32)
    k = jax.random.normal(kk, shape, jnp.float32)
    v = jax.random.normal(kv, shape, jnp.float32)

    ref = jax.nn.dot_product_attention(q, k, v)

    out = jax.jit(
        shard_map_compat(
            lambda a, b, c: ring_attention(a, b, c, "data"),
            mesh=mesh,
            in_specs=(P(None, "data"), P(None, "data"), P(None, "data")),
            out_specs=P(None, "data"),
            check=False,
        )
    )(q, k, v)
    np.testing.assert_allclose(
        host_collect(out), np.asarray(ref), atol=2e-5, rtol=1e-4
    )


def test_context_parallel_dit_matches_single_device():
    """Sharded-vs-single structural equivalence, pinned at f32 (in
    bf16 the ring's online-softmax accumulation order diverges from
    the fused attention by bf16 rounding, which is noise, not
    structure — the WAN head passes real signal so that noise is
    visible, unlike the old zero-init head)."""
    import dataclasses

    from comfyui_distributed_tpu.models.dit import VideoDiT

    cfg = dataclasses.replace(get_config("tiny-dit"), dtype="float32")
    dit = VideoDiT(cfg)
    mesh = build_mesh({"data": 8})

    x = jax.random.normal(jax.random.key(1), (1, 8, 4, 4, cfg.in_channels))
    t = jnp.array([250.0])
    ctx = jax.random.normal(jax.random.key(2), (1, 6, cfg.context_dim))
    params = dit.init(jax.random.key(0), x, t, ctx)

    single = dit.apply(params, x, t, ctx)
    sharded = video_forward_context_parallel(cfg, params, x, t, ctx, mesh)
    np.testing.assert_allclose(
        host_collect(sharded), np.asarray(single), atol=3e-5, rtol=1e-4
    )


def test_context_parallel_rejects_bad_frame_count():
    import pytest

    cfg = get_config("tiny-dit")
    dit = create_model("tiny-dit")
    mesh = build_mesh({"data": 8})
    x = jnp.zeros((1, 6, 4, 4, cfg.in_channels))  # 6 not divisible by 8
    ctx = jnp.zeros((1, 6, cfg.context_dim))
    params = dit.init(jax.random.key(0), jnp.zeros((1, 8, 4, 4, cfg.in_channels)),
                      jnp.zeros((1,)), ctx)
    with pytest.raises(ValueError):
        video_forward_context_parallel(cfg, params, x, jnp.zeros((1,)), ctx, mesh)
