"""Mesh-parallel tile execution: GrantSampler sharded dispatch parity,
bucket rounding, knob-driven worker-mesh construction, and the
tensor-parallel parameter sharding tier.

The tier-1 conftest forces 8 virtual CPU devices, so 4-participant
meshes exist without hardware; the dedicated CI job re-runs this suite
under XLA_FLAGS=--xla_force_host_platform_device_count=4 to pin the
exact fleet shape the acceptance names.
"""

import os
from unittest import mock

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.tile_pipeline import GrantSampler
from comfyui_distributed_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    advertised_capacity,
    auto_tp_size,
    build_mesh,
    mesh_summary,
    worker_mesh,
)
from comfyui_distributed_tpu.parallel.sharding import (
    maybe_shard_params,
    params_byte_size,
)

pytestmark = pytest.mark.skipif(
    jax.local_device_count() < 4, reason="needs >=4 (virtual) devices"
)


def _processor(params, tile, key, pos, neg, yx):
    """Deterministic per-tile stand-in: keyed noise + position term, the
    same shape contract as the production jitted tile processor."""
    noise = jax.random.normal(key, tile.shape)
    return tile * 2.0 + 0.05 * noise + yx[0] * 0.001


def _fixtures(num_tiles=16):
    extracted = (
        jnp.linspace(0.0, 1.0, num_tiles * 1 * 8 * 8 * 3)
        .reshape(num_tiles, 1, 8, 8, 3)
        .astype(jnp.float32)
    )
    positions = jnp.arange(num_tiles * 2).reshape(num_tiles, 2)
    return extracted, positions, jax.random.key(0)


def _mesh(n=4):
    return build_mesh(
        {DATA_AXIS: n, MODEL_AXIS: 1}, devices=jax.local_devices()[:n]
    )


# --- sharded dispatch parity ----------------------------------------------


@pytest.mark.parametrize("jit", [True, False], ids=["jitted", "eager-stub"])
def test_sampler_mesh_parity_full_ragged_and_single(jit):
    """The acceptance property at the sampler level: a 4-participant
    sharded dispatch produces byte-identical per-tile outputs to the
    1-device path — full buckets, ragged chunks (wraparound padding),
    and single tiles alike, for the jitted production shape AND the
    eager stub shape the chaos harness runs."""
    extracted, positions, key = _fixtures()
    process = jax.jit(_processor) if jit else _processor
    one = GrantSampler(
        process, None, extracted, key, positions, None, None, k_max=8
    )
    four = GrantSampler(
        process, None, extracted, key, positions, None, None, k_max=8,
        mesh=_mesh(4),
    )
    assert four.data_parallel == 4
    for idxs in ([0, 1, 2, 3, 4, 5, 6, 7], [3, 9, 11], [5], [1, 2]):
        a = np.asarray(one.sample(idxs))
        b = np.asarray(four.collect(four.sample(idxs)))
        np.testing.assert_array_equal(a, b)


def test_mesh_buckets_are_multiples_of_data_width():
    """Buckets round up to multiples of the data-axis width so the
    NamedSharding splits evenly, and the set stays bounded."""
    extracted, positions, key = _fixtures()
    sampler = GrantSampler(
        _processor, None, extracted, key, positions, None, None,
        k_max=8, mesh=_mesh(4),
    )
    assert sampler.buckets == (4, 8)
    assert all(b % 4 == 0 for b in sampler.buckets)
    # a 3-tile ragged chunk pads to the 4-bucket, not a fresh shape
    out = sampler.collect(sampler.sample([3, 9, 11]))
    assert np.asarray(out).shape[0] == 3
    assert sampler.buckets_used == {4}
    assert sampler.padded_tiles == 1


def test_sampled_batch_is_actually_sharded():
    """The dispatch must place the batch across the mesh (one shard per
    participant), not silently replicate onto one device."""
    extracted, positions, key = _fixtures()
    mesh = _mesh(4)
    sampler = GrantSampler(
        jax.jit(_processor), None, extracted, key, positions, None, None,
        k_max=8, mesh=mesh,
    )
    result = sampler.sample([0, 1, 2, 3, 4, 5, 6, 7])
    assert len(result.sharding.device_set) == 4
    host = sampler.collect(result)
    assert isinstance(host, np.ndarray) and host.shape[0] == 8


def test_sampler_k_max_clamps_to_data_width():
    """A caller-passed k_max below the participant count would starve
    chips every dispatch; the sampler clamps it up."""
    extracted, positions, key = _fixtures()
    sampler = GrantSampler(
        _processor, None, extracted, key, positions, None, None,
        k_max=1, mesh=_mesh(4),
    )
    assert sampler.k_max == 4
    assert sampler.chunks([0, 1, 2, 3, 4]) == [[0, 1, 2, 3], [4]]


# --- worker mesh construction (knob pair) ----------------------------------


def test_worker_mesh_cpu_defaults_off_and_knob_opts_in():
    assert worker_mesh() is None  # CPU: forced devices are a test artifact
    with mock.patch.dict(os.environ, {"CDT_MESH_SHAPE": "4,1"}):
        mesh = worker_mesh()
    assert mesh_summary(mesh) == {"data": 4, "model": 1, "devices": 4}
    assert advertised_capacity(mesh) == 4
    assert advertised_capacity(None) == 1


def test_worker_mesh_tp_knob_and_inference():
    n = jax.local_device_count()
    with mock.patch.dict(os.environ, {"CDT_TP_SIZE": "2"}):
        mesh = worker_mesh()
    summary = mesh_summary(mesh)
    assert summary["model"] == 2
    assert summary["data"] == n // 2
    # capacity advertises the DATA width only: model-axis chips serve
    # the same tiles, not more of them
    assert advertised_capacity(mesh) == n // 2
    with mock.patch.dict(os.environ, {"CDT_MESH_SHAPE": "-1,2"}):
        inferred = worker_mesh()
    assert mesh_summary(inferred) == summary


def test_worker_mesh_malformed_shape_falls_back():
    with mock.patch.dict(os.environ, {"CDT_MESH_SHAPE": "banana"}):
        assert worker_mesh() is None  # CPU default: no mesh


def test_worker_mesh_tp_keeps_explicit_data_pin():
    """CDT_TP_SIZE overrides only the model entry of CDT_MESH_SHAPE —
    an explicit data pin (chip subsetting on a shared host) survives,
    and only a combination exceeding the host reverts data to
    inferred."""
    n = jax.local_device_count()
    env = {"CDT_MESH_SHAPE": "2,1", "CDT_TP_SIZE": "2"}
    with mock.patch.dict(os.environ, env):
        mesh = worker_mesh()
    assert mesh_summary(mesh) == {"data": 2, "model": 2, "devices": 4}
    # conflicting pin (data x tp > host): data reverts to inferred
    env = {"CDT_MESH_SHAPE": f"{n},1", "CDT_TP_SIZE": "2"}
    with mock.patch.dict(os.environ, env):
        mesh = worker_mesh()
    assert mesh_summary(mesh) == {
        "data": n // 2, "model": 2, "devices": n,
    }


def test_tp_only_mesh_still_gauges_shape():
    """A tensor-parallel-only mesh (data=1, model>1 — the over-HBM
    sharded checkpoint) has no data fan-out but must still report its
    shape on cdt_mesh_devices."""
    from comfyui_distributed_tpu.telemetry.instruments import mesh_devices

    extracted, positions, key = _fixtures()
    tp_mesh = build_mesh(
        {DATA_AXIS: 1, MODEL_AXIS: 4}, devices=jax.local_devices()[:4]
    )
    sampler = GrantSampler(
        _processor, None, extracted, key, positions, None, None,
        k_max=4, role="tp-gauge-probe", mesh=tp_mesh,
    )
    assert sampler.data_parallel == 1
    g = mesh_devices()
    assert g.value(role="tp-gauge-probe", axis="model") == 4
    assert g.value(role="tp-gauge-probe", axis="data") == 1
    assert g.value(role="tp-gauge-probe", axis="total") == 4


def test_serving_mesh_summary_reports_recorded_mesh():
    """Status surfaces must report the mesh the elastic loop actually
    built — a knob-only re-derivation diverges exactly when the
    auto-TP budget rule shrank the data axis (it needs params_bytes
    the route doesn't have)."""
    import comfyui_distributed_tpu.parallel.mesh as mesh_mod

    saved = mesh_mod._serving_mesh_summary
    try:
        mesh_mod.note_serving_mesh(_mesh(4))
        assert mesh_mod.serving_mesh_summary() == {
            "data": 4, "model": 1, "devices": 4,
        }
        # the recorded shape wins over any knob-only resolution
        with mock.patch.dict(os.environ, {"CDT_MESH_SHAPE": "2,1"}):
            assert mesh_mod.serving_mesh_summary()["data"] == 4
        mesh_mod._serving_mesh_summary = None
        with mock.patch.dict(os.environ, {"CDT_MESH_SHAPE": "2,1"}):
            assert mesh_mod.serving_mesh_summary()["data"] == 2
    finally:
        mesh_mod._serving_mesh_summary = saved


def test_worker_mesh_non_divisible_knob_falls_back_not_crash():
    """Mesh knobs are advisory: a tp that doesn't divide the host must
    fall back to the single-device path (with a log line), never kill
    run_worker_loop before its first pull."""
    if jax.local_device_count() % 3 == 0:
        pytest.skip("tp=3 divides this host; not the non-divisible case")
    with mock.patch.dict(os.environ, {"CDT_TP_SIZE": "3"}):
        assert worker_mesh() is None


# --- tensor-parallel tier (HBM budget rule + param sharding) ---------------


def test_auto_tp_size_budget_rule():
    gib = 1 << 30
    with mock.patch.dict(os.environ, {"CDT_MESH_HBM_GB": "1"}):
        assert auto_tp_size(3 * gib, 8) == 4   # 3G/4 fits 1G budget
        assert auto_tp_size(100, 8) == 1       # already fits
        assert auto_tp_size(64 * gib, 4) == 4  # clamped to the fleet
        # non-power-of-two fleets clamp to the largest pow2 DIVIDING
        # the host — the data axis infers as n/tp, so tp=4 on 6 chips
        # would fail mesh construction instead of loading sharded
        assert auto_tp_size(64 * gib, 6) == 2
        assert auto_tp_size(64 * gib, 12) == 4
    # unset/zero budget disables the rule entirely
    assert auto_tp_size(64 * gib, 8) == 1


def test_maybe_shard_params_shards_model_axis_only_when_present():
    params = {"w": jnp.ones((16, 8)), "b": jnp.ones((3,))}
    assert maybe_shard_params(params, None) is params
    data_only = _mesh(4)
    assert maybe_shard_params(params, data_only) is params
    tp_mesh = build_mesh(
        {DATA_AXIS: 2, MODEL_AXIS: 2}, devices=jax.local_devices()[:4]
    )
    sharded = maybe_shard_params(params, tp_mesh)
    # largest divisible axis shards along the model axis; tiny
    # non-divisible leaves replicate
    assert str(sharded["w"].sharding.spec) == str((MODEL_AXIS, None)) or (
        sharded["w"].sharding.spec[0] == MODEL_AXIS
    )
    assert all(s is None for s in sharded["b"].sharding.spec)
    np.testing.assert_array_equal(np.asarray(sharded["w"]), np.ones((16, 8)))


def test_params_byte_size_counts_stored_bytes():
    params = {"w": jnp.ones((16, 8), jnp.float32), "b": jnp.ones((3,), jnp.bfloat16)}
    assert params_byte_size(params) == 16 * 8 * 4 + 3 * 2
