"""Seed-parallel generation on the virtual 8-device mesh: every
participant produces a distinct image; ordering is participant-first;
the result equals a single-device replay of the same folded keys."""

import jax
import numpy as np

from comfyui_distributed_tpu.models import pipeline as pl
from comfyui_distributed_tpu.parallel import build_mesh
from comfyui_distributed_tpu.parallel.collective import host_collect
from comfyui_distributed_tpu.parallel.generation import txt2img_parallel


def test_parallel_generation_distinct_and_deterministic():
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    mesh = build_mesh({"data": 8})
    out = txt2img_parallel(
        bundle, mesh, "a tree", height=32, width=32, steps=2, seed=5
    )
    imgs = host_collect(out)
    assert imgs.shape == (8, 32, 32, 3)
    assert np.isfinite(imgs).all()
    # independent seeds ⇒ distinct images
    assert len({imgs[i].tobytes() for i in range(8)}) == 8
    # deterministic across runs
    again = host_collect(
        txt2img_parallel(bundle, mesh, "a tree", height=32, width=32, steps=2, seed=5)
    )
    np.testing.assert_array_equal(imgs, again)


def test_parallel_matches_smaller_mesh_prefix():
    """Participant i's image depends only on (seed, i) — a 4-wide mesh
    must reproduce the first 4 images of the 8-wide mesh (elastic
    scaling invariant: adding workers never changes existing outputs)."""
    bundle = pl.load_pipeline("tiny-unet", seed=0)
    out8 = host_collect(
        txt2img_parallel(
            bundle, build_mesh({"data": 8}), "p", height=32, width=32, steps=2, seed=3
        )
    )
    mesh4 = build_mesh({"data": 4}, devices=jax.devices()[:4])
    out4 = host_collect(
        txt2img_parallel(
            bundle, mesh4, "p", height=32, width=32, steps=2, seed=3
        )
    )
    np.testing.assert_allclose(out4, out8[:4], atol=1e-6)
