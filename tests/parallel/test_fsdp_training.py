"""FSDP train step on the virtual mesh: UNet (the dryrun path) and
video DiT (the BASELINE wan-14b-FSDP configuration, tiny-sized)."""

import jax
import jax.numpy as jnp
import numpy as np
from comfyui_distributed_tpu.models import create_model, get_config
from comfyui_distributed_tpu.parallel import build_mesh
from comfyui_distributed_tpu.parallel.training import make_train_step

def _batch(rng, latents_shape, ctx_shape):
    return {
        "latents": jnp.asarray(rng.standard_normal(latents_shape), jnp.float32),
        "t": jnp.full((latents_shape[0],), 100.0),
        "context": jnp.asarray(rng.standard_normal(ctx_shape), jnp.float32),
        "noise": jnp.asarray(rng.standard_normal(latents_shape), jnp.float32),
    }


def test_dit_fsdp_train_step():
    """One DP x FSDP step of the video DiT: finite loss, updated
    params, parameters actually sharded over the model axis."""
    mesh = build_mesh({"data": 4, "model": 2})
    model = create_model("tiny-dit")
    cfg = get_config("tiny-dit")
    b = 4  # one sample per data-parallel group
    rng = np.random.default_rng(0)
    batch = _batch(rng, (b, 2, 8, 8, cfg.in_channels), (b, 8, cfg.context_dim))
    params = model.init(
        jax.random.key(0), batch["latents"], batch["t"], batch["context"]
    )

    step = make_train_step(model, mesh)
    new_params, loss = step(params, batch)
    assert np.isfinite(float(loss))

    flat_old = jax.tree_util.tree_leaves(params)
    flat_new = jax.tree_util.tree_leaves(new_params)
    changed = any(
        np.abs(np.asarray(o, np.float32) - np.asarray(n, np.float32)).max() > 0
        for o, n in zip(flat_old, flat_new)
    )
    assert changed

    # at least one large parameter is genuinely sharded on "model"
    sharded = [
        leaf for leaf in flat_new
        if hasattr(leaf, "sharding")
        and "model" in getattr(leaf.sharding, "spec", ())
    ]
    assert sharded, "no parameter carries a model-axis sharding"


def test_unet_fsdp_two_steps_progress():
    mesh = build_mesh({"data": 4, "model": 2})
    model = create_model("tiny-unet")
    cfg = get_config("tiny-unet")
    rng = np.random.default_rng(1)
    batch = _batch(rng, (4, 8, 8, cfg.in_channels), (4, 8, cfg.context_dim))
    params = model.init(
        jax.random.key(0), batch["latents"], batch["t"], batch["context"]
    )
    step = make_train_step(model, mesh)
    p1, l1 = step(params, batch)
    p2, l2 = step(p1, batch)
    assert np.isfinite(float(l1)) and np.isfinite(float(l2))
    assert float(l2) != float(l1)  # params moved between steps
