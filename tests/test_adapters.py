"""Adapter plane suite (comfyui_distributed_tpu/adapters/): request
parsing + content-hash identity, rank bucketing, merged-vs-segmented
math parity, cross-job executor slot isolation (bit-exact, jitted +
eager), one-program-per-rank-bucket compile guard, operand LRU cache +
admission cost, and the store/usage threading seams."""

import asyncio
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.adapters import (
    AdapterError,
    AdapterSpec,
    adapter_plan_key,
    adapter_signature,
    bundle_target_map,
    get_adapter_catalog,
    parse_adapter_specs,
    specs_from_wire,
    specs_to_wire,
)
from comfyui_distributed_tpu.adapters.cache import (
    AdapterOperandCache,
    adapter_admission_cost,
    operands_for_plan,
)
from comfyui_distributed_tpu.adapters.registry import AdapterCatalog
from comfyui_distributed_tpu.adapters.segmented import (
    SegmentOperands,
    build_operands,
    compose_operands,
    make_adapter_step,
    patch_params,
    rank_bucket_for,
    rank_buckets,
)
from comfyui_distributed_tpu.graph.batch_executor import (
    CrossJobExecutor,
    XJobHandle,
)
from comfyui_distributed_tpu.parallel.seeds import fold_job_key


def run(coro):
    return asyncio.run(coro)


# --------------------------------------------------------------------------
# request parsing + plan identity
# --------------------------------------------------------------------------


class TestParse:
    def test_none_and_empty_are_no_plan(self):
        assert parse_adapter_specs(None) == []
        assert parse_adapter_specs([]) == []

    def test_bare_string_defaults_strength(self):
        specs = parse_adapter_specs(["style"])
        assert specs == [AdapterSpec("style", 1.0, "")]

    def test_dict_entries(self):
        specs = parse_adapter_specs(
            [{"name": "a", "strength": 0.5}, {"name": "b"}]
        )
        assert [s.name for s in specs] == ["a", "b"]
        assert specs[0].strength == 0.5
        assert specs[1].strength == 1.0

    @pytest.mark.parametrize(
        "raw,fragment",
        [
            ("not-a-list", "must be a list"),
            ([{"strength": 1.0}], "name"),
            ([{"name": ""}], "name"),
            ([{"name": "a"}, {"name": "a"}], "repeats"),
            ([{"name": "a", "strength": "x"}], "number"),
            ([{"name": "a", "strength": float("nan")}], "finite"),
            ([{"name": "a", "strength": True}], "number"),
            ([42], "object or string"),
        ],
    )
    def test_rejections(self, raw, fragment):
        with pytest.raises(AdapterError, match=fragment):
            parse_adapter_specs(raw)

    def test_cap_at_max_adapters(self):
        raw = [{"name": f"a{i}"} for i in range(5)]
        with pytest.raises(AdapterError, match="at most 4"):
            parse_adapter_specs(raw)

    def test_wire_round_trip(self):
        specs = [
            AdapterSpec("a", 0.5, "ff" * 16),
            AdapterSpec("b", 1.5, "ee" * 16),
        ]
        assert specs_from_wire(specs_to_wire(specs)) == specs


class TestPlanKey:
    def test_unresolved_spec_raises(self):
        with pytest.raises(AdapterError, match="no content hash"):
            adapter_plan_key([AdapterSpec("a", 1.0, "")])

    def test_key_is_hash_strength_pairs_in_order(self):
        specs = [AdapterSpec("a", 0.5, "h1"), AdapterSpec("b", 1.0, "h2")]
        assert adapter_plan_key(specs) == (("h1", 0.5), ("h2", 1.0))

    def test_order_is_significant(self):
        a = [AdapterSpec("a", 1.0, "h1"), AdapterSpec("b", 1.0, "h2")]
        b = [AdapterSpec("b", 1.0, "h2"), AdapterSpec("a", 1.0, "h1")]
        assert adapter_plan_key(a) != adapter_plan_key(b)


# --------------------------------------------------------------------------
# rank buckets
# --------------------------------------------------------------------------


class TestRankBuckets:
    def test_defaults(self):
        assert rank_buckets() == (4, 8, 16, 32, 64)

    def test_bucket_for_rounds_up(self):
        assert rank_bucket_for(1) == 4
        assert rank_bucket_for(4) == 4
        assert rank_bucket_for(5) == 8
        assert rank_bucket_for(64) == 64

    def test_over_max_raises(self):
        with pytest.raises(AdapterError, match="exceeds the largest"):
            rank_bucket_for(65)

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("CDT_ADAPTER_RANK_BUCKETS", "2,16")
        assert rank_buckets() == (2, 16)
        assert rank_bucket_for(3) == 16

    @pytest.mark.parametrize("raw", ["abc", "0,4", "-4,8", ""])
    def test_bad_env_raises(self, monkeypatch, raw):
        monkeypatch.setenv("CDT_ADAPTER_RANK_BUCKETS", raw)
        with pytest.raises(AdapterError):
            rank_buckets()


# --------------------------------------------------------------------------
# catalog: content-hash identity + hash verification
# --------------------------------------------------------------------------


def _tiny_sd(seed=0, rank=2, dim=4, name="lora_unet_foo"):
    rng = np.random.default_rng(seed)
    return {
        f"{name}.lora_down.weight": rng.normal(size=(rank, dim)).astype(
            np.float32
        ),
        f"{name}.lora_up.weight": rng.normal(size=(dim, rank)).astype(
            np.float32
        ),
        f"{name}.alpha": np.float32(rank),
    }


class TestCatalog:
    def test_content_hash_is_content_not_name(self):
        cat = AdapterCatalog()
        cat.register_memory("a", _tiny_sd(seed=1))
        cat.register_memory("same-bytes", _tiny_sd(seed=1))
        cat.register_memory("b", _tiny_sd(seed=2))
        assert cat.content_hash("a") == cat.content_hash("same-bytes")
        assert cat.content_hash("a") != cat.content_hash("b")

    def test_resolve_stamps_hashes(self):
        cat = AdapterCatalog()
        cat.register_memory("a", _tiny_sd())
        (resolved,) = cat.resolve([AdapterSpec("a", 0.7)])
        assert resolved.content_hash == cat.content_hash("a")
        assert resolved.strength == 0.7

    def test_resolve_verifies_master_stamp(self):
        cat = AdapterCatalog()
        cat.register_memory("a", _tiny_sd(seed=1))
        good = cat.content_hash("a")
        # same hash passes
        cat.resolve([AdapterSpec("a", 1.0, good)])
        # divergent local bytes (same name) must fail loudly
        cat.register_memory("a", _tiny_sd(seed=2))
        with pytest.raises(AdapterError, match="content mismatch"):
            cat.resolve([AdapterSpec("a", 1.0, good)])

    def test_unknown_name_raises(self):
        with pytest.raises(AdapterError, match="unknown adapter"):
            AdapterCatalog().resolve([AdapterSpec("missing", 1.0)])

    def test_file_resolution_via_lora_dir(self, tmp_path, monkeypatch):
        from safetensors.numpy import save_file

        sd = _tiny_sd(seed=3)
        save_file(sd, str(tmp_path / "style.safetensors"))
        monkeypatch.setenv("CDT_LORA_DIR", str(tmp_path))
        cat = AdapterCatalog()
        assert "style" in cat.names()
        (resolved,) = cat.resolve([AdapterSpec("style", 1.0)])
        assert resolved.content_hash
        loaded = cat.load_state_dict("style")
        np.testing.assert_array_equal(
            loaded["lora_unet_foo.lora_down.weight"],
            sd["lora_unet_foo.lora_down.weight"],
        )

    def test_global_catalog_singleton(self):
        assert get_adapter_catalog() is get_adapter_catalog()


# --------------------------------------------------------------------------
# merged-vs-segmented parity (the numerics contract)
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def tiny_bundle():
    from comfyui_distributed_tpu.models import pipeline as pl

    return pl.load_pipeline("tiny-unet", seed=0)


def _flat_unet(tree):
    from comfyui_distributed_tpu.models.io import flatten_params

    return flatten_params(jax.device_get(tree["unet"]))


DENSE_NAME = "lora_unet_input_blocks_1_1_transformer_blocks_0_attn1_to_q"
PROJ_NAME = "lora_unet_input_blocks_1_1_proj_in"


def _lora_for(target_map, name, seed=0, rank=4, alpha=2.0, conv=False):
    rng = np.random.default_rng(seed)
    _, (dim_in, dim_out) = target_map[name]
    down = rng.normal(size=(rank, dim_in)).astype(np.float32)
    up = rng.normal(size=(dim_out, rank)).astype(np.float32)
    if conv:  # conv1x1-style layout some trainers emit for proj layers
        down = down[:, :, None, None]
        up = up[:, :, None, None]
    return {
        f"{name}.lora_down.weight": down,
        f"{name}.lora_up.weight": up,
        f"{name}.alpha": np.float32(alpha),
    }


class TestSegmentedParity:
    @pytest.mark.parametrize(
        "name,conv",
        [(DENSE_NAME, False), (PROJ_NAME, True)],
        ids=["dense", "proj-conv1x1"],
    )
    def test_patch_params_matches_merged_loader(
        self, tiny_bundle, name, conv
    ):
        """patch_params (the elastic whole-grant variant) lands on the
        same kernels as models/lora.apply_lora for both target
        families, including the strength scale."""
        from comfyui_distributed_tpu.models import get_config
        from comfyui_distributed_tpu.models.lora import apply_lora

        target_map = bundle_target_map(tiny_bundle)
        sd = _lora_for(target_map, name, seed=5, conv=conv)
        merged, unmatched = apply_lora(
            {"unet": tiny_bundle.params["unet"]},
            sd,
            get_config("tiny-unet"),
            strength=0.7,
        )
        assert unmatched == []
        ops = build_operands(sd, target_map)
        patched = patch_params(tiny_bundle.params, ops, scale=0.7)
        path = target_map[name][0][len("unet/"):]
        np.testing.assert_allclose(
            _flat_unet(patched)[path], _flat_unet(merged)[path], rtol=1e-5
        )
        # a leaf the adapter does not touch is BIT-identical: the zero
        # operand rows contribute exactly 0.0
        other = next(
            p[len("unet/"):]
            for n, (p, _) in sorted(target_map.items())
            if n != name
        )
        np.testing.assert_array_equal(
            _flat_unet(patched)[other],
            _flat_unet({"unet": tiny_bundle.params["unet"]})[other],
        )

    def test_rank_padding_is_exact(self, tiny_bundle):
        """The same adapter padded to a LARGER rank bucket produces
        bit-identical patched kernels — zero rows are exact."""
        target_map = bundle_target_map(tiny_bundle)
        sd = _lora_for(target_map, DENSE_NAME, seed=6, rank=3)
        small = build_operands(sd, target_map, bucket=4)
        large = build_operands(sd, target_map, bucket=8)
        assert small.rank_bucket == 4 and large.rank_bucket == 8
        path = target_map[DENSE_NAME][0][len("unet/"):]
        a = _flat_unet(patch_params(tiny_bundle.params, small, scale=1.3))
        b = _flat_unet(patch_params(tiny_bundle.params, large, scale=1.3))
        np.testing.assert_array_equal(a[path], b[path])

    def test_bucket_smaller_than_rank_raises(self, tiny_bundle):
        target_map = bundle_target_map(tiny_bundle)
        sd = _lora_for(target_map, DENSE_NAME, rank=6)
        with pytest.raises(AdapterError, match="exceeds requested bucket"):
            build_operands(sd, target_map, bucket=4)

    def test_te_modules_are_skipped_not_fatal(self, tiny_bundle):
        """lora_te* rides only the merged loader; the backbone-only
        segmented tier skips it and still builds unet operands."""
        target_map = bundle_target_map(tiny_bundle)
        sd = _lora_for(target_map, DENSE_NAME, seed=7)
        sd.update(_tiny_sd(name="lora_te_text_model_encoder_layers_0_mlp_fc1"))
        ops = build_operands(sd, target_map)
        assert any(np.abs(d).sum() > 0 for d in ops.downs)

    def test_compose_matches_sequential_merge(self, tiny_bundle):
        """Two stacked adapters (rank concat, strengths folded) land on
        the same kernels as merging them one after the other."""
        from comfyui_distributed_tpu.models import get_config
        from comfyui_distributed_tpu.models.lora import apply_lora

        cfg = get_config("tiny-unet")
        target_map = bundle_target_map(tiny_bundle)
        sd_a = _lora_for(target_map, DENSE_NAME, seed=8, rank=2)
        sd_b = _lora_for(target_map, PROJ_NAME, seed=9, rank=3)
        merged, _ = apply_lora(
            {"unet": tiny_bundle.params["unet"]}, sd_a, cfg, strength=0.5
        )
        merged, _ = apply_lora(merged, sd_b, cfg, strength=1.5)
        ops_a = build_operands(sd_a, target_map)
        ops_b = build_operands(sd_b, target_map)
        composed = compose_operands([ops_a, ops_b], [0.5, 1.5])
        assert composed.rank_bucket >= ops_a.rank_bucket + ops_b.rank_bucket
        assert composed.scale == 1.0
        patched = patch_params(tiny_bundle.params, composed)
        for name in (DENSE_NAME, PROJ_NAME):
            path = target_map[name][0][len("unet/"):]
            np.testing.assert_allclose(
                _flat_unet(patched)[path],
                _flat_unet(merged)[path],
                rtol=1e-5,
                atol=1e-6,
            )

    def test_compose_rejects_mismatched_target_maps(self):
        a = SegmentOperands(("p1",), (np.zeros((4, 2), np.float32),),
                            (np.zeros((2, 4), np.float32),), 1.0, 4, 0, "a")
        b = SegmentOperands(("p2",), (np.zeros((4, 2), np.float32),),
                            (np.zeros((2, 4), np.float32),), 1.0, 4, 0, "b")
        with pytest.raises(AdapterError, match="different target maps"):
            compose_operands([a, b], [1.0, 1.0])


# --------------------------------------------------------------------------
# signature extension
# --------------------------------------------------------------------------


def _stub_ops(seed=0, rank=2, bucket=4, dim=3, scale=1.0,
              paths=("unet/dense/kernel",)):
    """Hand-built operands against a toy params tree (the executor
    tests' target map: one 3x3 kernel)."""
    rng = np.random.default_rng(seed)
    downs, ups = [], []
    for _ in paths:
        down = np.zeros((bucket, dim), np.float32)
        up = np.zeros((dim, bucket), np.float32)
        down[:rank] = 0.1 * rng.normal(size=(rank, dim))
        up[:, :rank] = 0.1 * rng.normal(size=(dim, rank))
        downs.append(down)
        ups.append(up)
    nbytes = sum(a.nbytes for a in downs) + sum(a.nbytes for a in ups)
    return SegmentOperands(
        paths=tuple(paths), downs=tuple(downs), ups=tuple(ups),
        scale=float(scale), rank_bucket=bucket, nbytes=nbytes,
        fingerprint=f"stub-{seed}",
    )


class TestSignature:
    def test_extends_base_signature(self):
        sig = adapter_signature(("stub", 1), _stub_ops())
        assert sig[:2] == ("stub", 1)
        kind, bucket, digest = sig[-1]
        assert kind == "adapter" and bucket == 4 and digest

    def test_same_bucket_different_content_shares_signature(self):
        # content and strength are traced operands — NOT signature
        base = ("stub",)
        a = adapter_signature(base, _stub_ops(seed=1, scale=0.5))
        b = adapter_signature(base, _stub_ops(seed=2, scale=2.0))
        assert a == b

    def test_bucket_changes_signature(self):
        base = ("stub",)
        assert adapter_signature(base, _stub_ops(bucket=4)) != (
            adapter_signature(base, _stub_ops(bucket=8))
        )

    def test_target_paths_change_signature(self):
        base = ("stub",)
        assert adapter_signature(base, _stub_ops()) != adapter_signature(
            base, _stub_ops(paths=("unet/other/kernel",))
        )


# --------------------------------------------------------------------------
# executor: slot isolation + compile-count guard
# --------------------------------------------------------------------------

N_STEPS = 3


def _params(dim=3):
    # identity-ish kernel so the matmul path stays well-conditioned
    return {
        "unet": {
            "dense": {"kernel": jnp.eye(dim, dtype=jnp.float32) * 0.9}
        }
    }


def _weight_proc(n_steps=N_STEPS, signature=("wstub",), jit=False,
                 trace_log=None):
    """A stepwise stub whose step actually CONSUMES the params kernel,
    so a per-slot weight patch is visible in the output."""

    def init(params, tile, key):
        return tile + 0.0

    def step(params, x, key, pos, neg, yx, i):
        if trace_log is not None:
            trace_log.append(1)
        w = params["unet"]["dense"]["kernel"]
        ki = jax.random.fold_in(key, i)
        return (
            jnp.einsum("hwc,cd->hwd", x, w)
            + 0.01 * jax.random.normal(ki, x.shape)
            + 0.001 * pos
        )

    def finish(params, x):
        return jnp.clip(x, -10.0, 10.0)

    return types.SimpleNamespace(
        init=init,
        step=jax.jit(step) if jit else step,
        finish=finish,
        n_steps=n_steps,
        signature=tuple(signature),
    )


class _FakeMaster:
    def __init__(self, n_tiles):
        self.pending = list(range(n_tiles))

    def pull(self):
        if not self.pending:
            return None
        grant, self.pending = self.pending, []
        return {"tile_idxs": grant, "checkpoints": {}}

    def release(self, idxs, cks):
        self.pending = sorted(set(self.pending) | set(idxs))


def _make_job(job_id, n_tiles, seed, *, proc, params, adapter=None):
    master = _FakeMaster(n_tiles)
    rng = np.random.default_rng(seed)
    extracted = jnp.asarray(rng.random((n_tiles, 4, 4, 3)), jnp.float32)
    outs = {}
    handle = XJobHandle(
        job_id=job_id,
        proc=proc,
        params=params,
        extracted=extracted,
        positions=jnp.zeros((n_tiles, 2), jnp.int32),
        pos=jnp.float32(seed),
        neg=jnp.float32(0),
        base_key=fold_job_key(jax.random.key(seed), job_id),
        pull=master.pull,
        emit=lambda idx, arr: outs.__setitem__(int(idx), np.asarray(arr)),
        flush=lambda final: None,
        release=master.release,
        adapter=adapter,
    )
    return handle, outs


def _solo(job_id, n_tiles, seed, *, proc, params, adapter=None, k_max=8):
    ex = CrossJobExecutor(k_max=k_max)
    handle, outs = _make_job(
        job_id, n_tiles, seed, proc=proc, params=params, adapter=adapter
    )
    ex.register(handle)
    ex.run()
    return outs


class TestExecutorSlotIsolation:
    @pytest.mark.parametrize("jit", [False, True], ids=["eager", "jitted"])
    def test_different_adapters_batched_bit_identical_to_solo(self, jit):
        """Two jobs wearing DIFFERENT adapters share one batch (same
        rank bucket → same extended signature) and each tile's output
        is bit-identical to sampling that job alone."""
        proc = _weight_proc(jit=jit)
        params = _params()
        ops_a = _stub_ops(seed=1, scale=0.8)
        ops_b = _stub_ops(seed=2, scale=1.2)
        ex = CrossJobExecutor(k_max=4)
        h1, o1 = _make_job("job-a", 2, 1, proc=proc, params=params,
                           adapter=ops_a)
        h2, o2 = _make_job("job-b", 2, 2, proc=proc, params=params,
                           adapter=ops_b)
        assert h1.sig == h2.sig  # they CAN batch together
        ex.register(h1)
        ex.register(h2)
        ex.run()
        solo_a = _solo("job-a", 2, 1, proc=proc, params=params,
                       adapter=ops_a)
        solo_b = _solo("job-b", 2, 2, proc=proc, params=params,
                       adapter=ops_b)
        for i in range(2):
            np.testing.assert_array_equal(o1[i], solo_a[i])
            np.testing.assert_array_equal(o2[i], solo_b[i])

    def test_adapter_actually_changes_output(self):
        proc = _weight_proc()
        params = _params()
        base = _solo("job-a", 1, 1, proc=proc, params=params)
        worn = _solo("job-a", 1, 1, proc=proc, params=params,
                     adapter=_stub_ops(seed=3))
        assert not np.array_equal(base[0], worn[0])

    def test_adapterless_keeps_original_signature_and_output(self):
        """An adapter-less job never shares a signature group with
        adapter jobs, and its output is bit-identical to a run where
        the adapter plane does not exist at all."""
        proc = _weight_proc()
        params = _params()
        h_plain, _ = _make_job("plain", 1, 5, proc=proc, params=params)
        h_worn, _ = _make_job("worn", 1, 6, proc=proc, params=params,
                              adapter=_stub_ops(seed=4))
        assert h_plain.sig == proc.signature
        assert h_worn.sig != proc.signature

        ex = CrossJobExecutor(k_max=4)
        hp, op_ = _make_job("plain", 1, 5, proc=proc, params=params)
        hw, _ = _make_job("worn", 1, 6, proc=proc, params=params,
                          adapter=_stub_ops(seed=4))
        ex.register(hp)
        ex.register(hw)
        ex.run()
        baseline = _solo("plain", 1, 5, proc=proc, params=params)
        np.testing.assert_array_equal(op_[0], baseline[0])

    def test_mixed_strengths_ride_as_traced_scale(self):
        """Same adapter content at different strengths batches under
        one signature and stays bit-identical to solo."""
        proc = _weight_proc(jit=True)
        params = _params()
        weak = _stub_ops(seed=7, scale=0.25)
        strong = _stub_ops(seed=7, scale=4.0)
        ex = CrossJobExecutor(k_max=4)
        h1, o1 = _make_job("weak", 1, 1, proc=proc, params=params,
                           adapter=weak)
        h2, o2 = _make_job("strong", 1, 1, proc=proc, params=params,
                           adapter=strong)
        ex.register(h1)
        ex.register(h2)
        ex.run()
        np.testing.assert_array_equal(
            o1[0], _solo("weak", 1, 1, proc=proc, params=params,
                         adapter=weak)[0]
        )
        np.testing.assert_array_equal(
            o2[0], _solo("strong", 1, 1, proc=proc, params=params,
                         adapter=strong)[0]
        )
        assert not np.array_equal(o1[0], o2[0])


class TestCompileGuard:
    def test_n_distinct_adapters_one_trace(self):
        """N jobs wearing N DIFFERENT same-rank adapters run under ONE
        traced program: adapter content is an operand, not a signature.
        Trace count == compile count for a jitted step."""
        trace_log = []
        proc = _weight_proc(jit=True, trace_log=trace_log)
        params = _params()
        ex = CrossJobExecutor(k_max=4)
        handles = []
        for i in range(3):
            h, _ = _make_job(f"job-{i}", 1, i + 1, proc=proc, params=params,
                             adapter=_stub_ops(seed=10 + i))
            handles.append(h)
            ex.register(h)
        ex.run()
        assert all(h.done and h.error is None for h in handles)
        # every dispatch is the same (signature, bucket): one trace
        assert len(trace_log) == 1


# --------------------------------------------------------------------------
# operand cache + admission cost
# --------------------------------------------------------------------------


class TestOperandCache:
    def test_hit_miss_accounting(self):
        cache = AdapterOperandCache(budget_bytes=1 << 20)
        ops = _stub_ops(seed=1)
        built = []

        def build():
            built.append(1)
            return ops

        got, hit = cache.get_or_build(("k1",), ("h1",), build)
        assert got is ops and not hit
        got, hit = cache.get_or_build(("k1",), ("h1",), build)
        assert got is ops and hit
        assert len(built) == 1
        assert cache.stats()["hits"] == 1
        assert cache.stats()["misses"] == 1

    def test_lru_eviction_under_budget(self):
        ops = _stub_ops(seed=1)
        cache = AdapterOperandCache(budget_bytes=int(ops.nbytes * 2.5))
        for i in range(3):
            cache.get_or_build((f"k{i}",), (f"h{i}",), lambda: ops)
        stats = cache.stats()
        assert stats["evictions"] >= 1
        assert stats["bytes"] <= cache.budget_bytes
        # oldest entry (and its hash ref) evicted first
        assert not cache.contains_hash("h0")
        assert cache.contains_hash("h2")

    def test_oversized_entry_not_cached(self):
        ops = _stub_ops(seed=1)
        cache = AdapterOperandCache(budget_bytes=ops.nbytes - 1)
        got, hit = cache.get_or_build(("k",), ("h",), lambda: ops)
        assert got is ops and not hit
        assert cache.stats()["entries"] == 0
        assert not cache.contains_hash("h")

    def test_operands_for_plan_strength_independent_caching(self):
        cat = AdapterCatalog()
        cat.register_memory("a", _tiny_sd(seed=1, dim=3, name="lora_unet_x"))
        (spec,) = cat.resolve([AdapterSpec("a", 0.5)])
        target_map = {"lora_unet_x": ("unet/dense/kernel", (3, 3))}
        cache = AdapterOperandCache(budget_bytes=1 << 20)
        ops1 = operands_for_plan([spec], target_map, catalog=cat, cache=cache)
        ops2 = operands_for_plan(
            [AdapterSpec("a", 2.0, spec.content_hash)],
            target_map, catalog=cat, cache=cache,
        )
        assert cache.stats()["misses"] == 1
        assert cache.stats()["hits"] == 1  # strength sweep reuses entry
        assert ops1.scale == 0.5 and ops2.scale == 2.0
        np.testing.assert_array_equal(ops1.downs[0], ops2.downs[0])

    def test_operands_for_plan_empty_or_unresolved_raises(self):
        with pytest.raises(AdapterError, match="empty plan"):
            operands_for_plan([], {})
        with pytest.raises(AdapterError, match="no content hash"):
            operands_for_plan(
                [AdapterSpec("a", 1.0)],
                {"lora_unet_x": ("unet/dense/kernel", (3, 3))},
            )

    def test_admission_cost_knob(self, monkeypatch):
        # default (1.0) = seam off, even for unknown hashes
        assert adapter_admission_cost(("deadbeef",)) == 1.0
        monkeypatch.setenv("CDT_ADAPTER_COLD_COST", "2.5")
        assert adapter_admission_cost(()) == 1.0
        assert adapter_admission_cost(("not-resident",)) == 2.5

    def test_admission_cost_warm_plan_is_free(self, monkeypatch):
        from comfyui_distributed_tpu.adapters.cache import (
            _reset_adapter_cache_for_tests,
            get_adapter_cache,
        )

        monkeypatch.setenv("CDT_ADAPTER_COLD_COST", "3.0")
        _reset_adapter_cache_for_tests()
        try:
            cache = get_adapter_cache()
            cache.get_or_build(("k",), ("warmhash",), lambda: _stub_ops())
            assert adapter_admission_cost(("warmhash",)) == 1.0
            assert adapter_admission_cost(("warmhash", "coldhash")) == 3.0
        finally:
            _reset_adapter_cache_for_tests()


# --------------------------------------------------------------------------
# store threading + usage attribution
# --------------------------------------------------------------------------

WIRE = [{"name": "style", "strength": 0.5, "content_hash": "ab" * 16}]


class TestStoreThreading:
    def test_note_then_init_stamps_plan(self):
        from comfyui_distributed_tpu.jobs import JobStore

        store = JobStore()

        async def scenario():
            store.note_job_adapters("t", WIRE)
            assert await store.peek_job_adapters("t") == WIRE
            job = await store.init_tile_job("t", [0, 1])
            assert job.adapters == WIRE
            # stamped record now answers the peek (non-destructive)
            assert await store.peek_job_adapters("t") == WIRE

        run(scenario())

    def test_malformed_note_is_dropped(self):
        from comfyui_distributed_tpu.jobs import JobStore

        store = JobStore()
        store.note_job_adapters("t", [{"strength": 2.0}])  # no name

        async def scenario():
            assert await store.peek_job_adapters("t") == []
            job = await store.init_tile_job("t", [0])
            assert job.adapters == []

        run(scenario())

    def test_journal_replay_restores_plan(self, tmp_path):
        """job_init journals the wire plan; recovery re-serves it so a
        restarted master's job_status still carries the adapters."""
        from comfyui_distributed_tpu.durability.journal import Journal
        from comfyui_distributed_tpu.durability.recovery import recover_state

        journal = Journal(str(tmp_path), fsync_every=1)
        journal.append(
            {"type": "job_init", "job": "j", "kind": "tile",
             "batched": True, "tasks": [0, 1], "adapters": WIRE}
        )
        journal.close()
        state, _ = recover_state(str(tmp_path))
        assert state["jobs"]["j"]["adapters"] == WIRE

    def test_recovered_store_serves_plan(self, tmp_path):
        from comfyui_distributed_tpu.durability.journal import Journal
        from comfyui_distributed_tpu.durability.recovery import recover
        from comfyui_distributed_tpu.jobs import JobStore

        journal = Journal(str(tmp_path), fsync_every=1)
        journal.append(
            {"type": "job_init", "job": "j", "kind": "tile",
             "batched": True, "tasks": [0], "adapters": WIRE}
        )
        journal.close()
        store = JobStore()
        recover(str(tmp_path), store)

        async def scenario():
            assert await store.peek_job_adapters("j") == WIRE

        run(scenario())

    def test_legacy_record_without_adapters_restores_empty(self, tmp_path):
        from comfyui_distributed_tpu.durability.journal import Journal
        from comfyui_distributed_tpu.durability.recovery import recover_state

        journal = Journal(str(tmp_path), fsync_every=1)
        journal.append(
            {"type": "job_init", "job": "j", "kind": "tile",
             "batched": True, "tasks": [0]}
        )
        journal.close()
        state, _ = recover_state(str(tmp_path))
        assert state["jobs"]["j"]["adapters"] == []


class TestUsageAttribution:
    def test_rollup_gains_adapter_section(self):
        from comfyui_distributed_tpu.telemetry.usage import UsageMeter

        meter = UsageMeter(clock=lambda: 0.0)
        meter.note_job_attrs("j1", "tenant-a", "")
        meter.note_job_adapter("j1", "hash1:0.5")
        meter.note_dispatch(
            tier="xjob", role="worker", elapsed_s=1.0, chips=1,
            slots=[{"job_id": "j1", "kind": "real"}],
        )
        meter.note_tiles("worker", "j1", 2)
        roll = meter.rollup()
        assert "hash1:0.5" in roll["adapters"]
        assert roll["adapters"]["hash1:0.5"]["tiles"] == 2
        assert roll["jobs"]["j1"]["adapter"] == "hash1:0.5"

    def test_adapterless_job_absent_from_adapter_rollup(self):
        from comfyui_distributed_tpu.telemetry.usage import UsageMeter

        meter = UsageMeter(clock=lambda: 0.0)
        meter.note_dispatch(
            tier="xjob", role="worker", elapsed_s=1.0, chips=1,
            slots=[{"job_id": "j1", "kind": "real"}],
        )
        roll = meter.rollup()
        assert roll["adapters"] == {}
        assert roll["jobs"]["j1"]["adapter"] == ""


# --------------------------------------------------------------------------
# adapter step wrapper (unit)
# --------------------------------------------------------------------------


class TestAdapterStep:
    def test_wrapper_patches_then_delegates(self):
        seen = {}

        def base_step(params, x, key, pos, neg, yx, i):
            seen["kernel"] = params["unet"]["dense"]["kernel"]
            return x

        ops = _stub_ops(seed=1)
        step = make_adapter_step(base_step, ops.paths)
        params = _params()
        x = jnp.zeros((4, 4, 3), jnp.float32)
        step(params, x, jax.random.key(0), 0.0, 0.0,
             jnp.zeros(2, jnp.int32), 0,
             tuple(jnp.asarray(d) for d in ops.downs),
             tuple(jnp.asarray(u) for u in ops.ups),
             jnp.float32(ops.scale))
        expect = np.asarray(
            params["unet"]["dense"]["kernel"], np.float32
        ) + ops.scale * (ops.downs[0].T @ ops.ups[0].T)
        np.testing.assert_allclose(
            np.asarray(seen["kernel"]), expect, rtol=1e-5
        )
        # the original tree is untouched (copy-on-write)
        np.testing.assert_array_equal(
            np.asarray(params["unet"]["dense"]["kernel"]),
            np.asarray(_params()["unet"]["dense"]["kernel"]),
        )
