"""The bench harness's cold->warm tile-cache A/B: the `cache` block
stamped into tiny datums must prove the cached serving floor is real
(warm strictly faster, 100% probe hits, zero worker dispatches) and
honest (bit-identity verdict, no effective-rate fantasy at miss share
zero)."""

import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _load_bench():
    import importlib.util

    spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.chaos
def test_cache_ab_block_shape_and_verdicts():
    bench = _load_bench()
    block = bench._measure_cache_ab()
    assert block is not None
    assert block["tiles"] > 0
    assert block["bit_identical"] is True
    # warm run: every probe hit, every tile settled from cache, no
    # worker ever dispatched — the near-free serving path end to end
    assert block["warm"]["hit_rate"] == 1.0
    assert block["warm"]["settled"] == block["tiles"]
    assert block["warm"]["worker_tiles"] == 0
    # the headline: cached serving is strictly faster than recompute
    assert block["warm"]["elapsed_s"] < block["cold"]["elapsed_s"]
    assert block["speedup"] > 1.0
    # honesty rule: miss share 0 makes the amortized rate unbounded —
    # it must be null, never a fantasy number
    assert block["tiles_per_sec_chip_effective"] is None
    assert block["ram_bytes"] > 0
