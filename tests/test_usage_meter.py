"""Usage metering & chip-time attribution (telemetry/usage.py).

Pins the plane's load-bearing properties:

- the conservation identity is EXACT (integer ns) per record and
  cumulatively, on BOTH execution tiers, jitted and eager-stub;
- slot classification: padding and recompute slots charge waste
  buckets, real slots charge their owning (job → tenant, lane);
- store-family waste (speculation losers, poison retries) lands in its
  buckets without touching the dispatch identity;
- worker-snapshot adoption is delta-based with a counter-reset clamp
  (a restarted worker can never produce a negative delta);
- idle jobs/tenants evict (flat memory under churn) and fire the
  series-eviction seam;
- the measured cost model (chip-s-per-tile EWMA ratio) feeds DRR
  admission cost behind CDT_USAGE_COST;
- rollups are replay-stable (byte-identical for the same record
  sequence — the CDT004 scope's point).
"""

import json
import types

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from comfyui_distributed_tpu.telemetry.usage import (
    SLOT_PADDING,
    SLOT_REAL,
    SLOT_RECOMPUTE,
    UsageAggregator,
    UsageMeter,
)

pytestmark = pytest.mark.fast


def _dispatch(meter, *, role="worker", elapsed=0.0101, chips=1, slots=None):
    return meter.note_dispatch(
        tier="xjob", role=role, elapsed_s=elapsed, chips=chips,
        slots=slots or [{"job_id": "j", "kind": SLOT_REAL}],
    )


# --------------------------------------------------------------------------
# conservation: exact, per record and cumulative
# --------------------------------------------------------------------------


def test_record_conservation_exact_with_integer_remainder():
    meter = UsageMeter()
    # 0.0101 s x 3 chips = 30_300_000 ns over 7 slots -> remainder 5 ns
    slots = (
        [{"job_id": "a", "kind": SLOT_REAL}] * 3
        + [{"job_id": "b", "kind": SLOT_RECOMPUTE}] * 2
        + [{"job_id": "", "kind": SLOT_PADDING}] * 2
    )
    rec = _dispatch(meter, elapsed=0.0101, chips=3, slots=slots)
    assert rec["chip_ns"] == 30_300_000
    assert (
        rec["attributed_ns"] + rec["waste_ns"] + rec["overhead_ns"]
        == rec["chip_ns"]
    )
    assert rec["overhead_ns"] == 30_300_000 - (30_300_000 // 7) * 7
    totals = meter.totals()
    assert totals["conserved"] is True
    assert totals["waste_ns"]["padding"] == 2 * (30_300_000 // 7)
    assert totals["waste_ns"]["preempt_recompute"] == 2 * (30_300_000 // 7)


def test_cumulative_conservation_over_many_records():
    meter = UsageMeter()
    rng = np.random.default_rng(0)
    for i in range(200):
        n_real = int(rng.integers(1, 5))
        n_pad = int(rng.integers(0, 3))
        n_rec = int(rng.integers(0, 2))
        slots = (
            [{"job_id": f"j{i % 7}", "kind": SLOT_REAL}] * n_real
            + [{"job_id": f"j{i % 7}", "kind": SLOT_RECOMPUTE}] * n_rec
            + [{"job_id": "", "kind": SLOT_PADDING}] * n_pad
        )
        _dispatch(
            meter, elapsed=float(rng.random()) * 0.01,
            chips=int(rng.integers(1, 5)), slots=slots,
        )
    totals = meter.totals()
    assert totals["conserved"] is True
    # the identity the CI smoke also pins, spelled out:
    assert (
        totals["attributed_ns"]
        + totals["dispatch_waste_ns"]
        + totals["overhead_ns"]
        == totals["dispatch_chip_ns"]
    )


def test_store_family_waste_outside_dispatch_identity():
    meter = UsageMeter()
    _dispatch(meter, role="master")
    meter.note_waste("master", "speculation", 0.5, job_id="j")
    meter.note_waste("master", "poison_retry", 0.25)
    totals = meter.totals()
    assert totals["conserved"] is True  # dispatch family untouched
    assert totals["waste_ns"]["speculation"] == 500_000_000
    assert totals["waste_ns"]["poison_retry"] == 250_000_000
    assert totals["dispatch_waste_ns"] == 0


# --------------------------------------------------------------------------
# tier conservation: scan (GrantSampler) and xjob (CrossJobExecutor),
# jitted and eager-stub
# --------------------------------------------------------------------------


def _stub(params, tile, key, pos, neg, yx):
    return tile * 2.0 + 1.0


@pytest.mark.parametrize("jit", [False, True], ids=["eager", "jitted"])
def test_scan_tier_conservation_and_padding(jit):
    from comfyui_distributed_tpu.graph.tile_pipeline import GrantSampler

    meter = UsageMeter()
    process = jax.jit(_stub) if jit else _stub
    sampler = GrantSampler(
        process, None, jnp.ones((3, 4, 4, 3), jnp.float32),
        jax.random.key(0), jnp.zeros((3, 2), jnp.int32), None, None,
        k_max=4, job_id="scan-job", tenant="tenant-s", usage_meter=meter,
    )
    out = sampler.sample([0, 1, 2])  # ragged: pads to the 4-bucket
    assert out.shape[0] == 3
    totals = meter.totals()
    assert totals["conserved"] is True
    assert totals["dispatch_chip_ns"] > 0
    assert totals["waste_ns"]["padding"] > 0
    snap = meter.snapshot("worker")
    assert snap["jobs"]["scan-job"]["tiles"] == 3
    # serial (k_max=1) reference path meters too, without padding
    serial_meter = UsageMeter()
    serial = GrantSampler(
        process, None, jnp.ones((3, 4, 4, 3), jnp.float32),
        jax.random.key(0), jnp.zeros((3, 2), jnp.int32), None, None,
        k_max=1, job_id="scan-job", usage_meter=serial_meter,
    )
    serial.sample([0, 1])
    serial_totals = serial_meter.totals()
    assert serial_totals["conserved"] is True
    assert "padding" not in serial_totals["waste_ns"]


@pytest.mark.parametrize("jit", [False, True], ids=["eager", "jitted"])
def test_xjob_tier_conservation_and_attribution(jit):
    from comfyui_distributed_tpu.graph.batch_executor import (
        CrossJobExecutor,
        XJobHandle,
    )
    from comfyui_distributed_tpu.parallel.seeds import fold_job_key

    def init(params, tile, key):
        return tile + 0.0

    def step(params, x, key, pos, neg, yx, i):
        return x + 0.25

    def finish(params, x):
        return x

    proc = types.SimpleNamespace(
        init=init, step=jax.jit(step) if jit else step, finish=finish,
        n_steps=2, signature=("usage-stub",),
    )
    meter = UsageMeter()
    executor = CrossJobExecutor(k_max=8, usage_meter=meter)
    outs: dict[str, dict] = {}
    for job_id, tenant in (("uj-a", "tenant-a"), ("uj-b", "tenant-b")):
        pending = [list(range(3))]  # one 3-tile grant, then drained

        def pull(pending=pending):
            if pending:
                return {"tile_idxs": pending.pop(), "checkpoints": {}}
            return None

        outs[job_id] = {}

        def emit(idx, arr, sink=outs[job_id]):
            sink[int(idx)] = np.asarray(arr)

        executor.register(
            XJobHandle(
                job_id=job_id,
                proc=proc,
                params=None,
                extracted=jnp.ones((3, 4, 4, 3), jnp.float32),
                positions=jnp.zeros((3, 2), jnp.int32),
                pos=jnp.float32(0),
                neg=jnp.float32(0),
                base_key=fold_job_key(jax.random.key(1), job_id),
                pull=pull,
                emit=emit,
                flush=lambda final: None,
                tenant=tenant,
                lane="batch",
            )
        )
    executor.run()
    assert all(len(v) == 3 for v in outs.values())
    totals = meter.totals()
    assert totals["conserved"] is True
    assert totals["dispatch_chip_ns"] > 0
    rollup = meter.rollup()
    # both tenants charged; the 6-tile cross-job batches pad to 8
    assert rollup["tenants"]["tenant-a"]["chip_s"] > 0
    assert rollup["tenants"]["tenant-b"]["chip_s"] > 0
    assert rollup["tenants"]["tenant-a"]["tiles"] == 3
    assert totals["waste_ns"].get("padding", 0) > 0
    assert rollup["lanes"]["batch"]["tiles"] == 6


def test_xjob_recompute_slots_charge_waste_not_tenant():
    """A tile evicted at step S and re-adopted WITHOUT a checkpoint
    re-runs steps < S as waste{preempt_recompute}; its remaining steps
    charge the tenant."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_xjob

    spec = {
        "job_id": "u-batch", "seed": 7, "tenant": "tenant-a",
        "lane": "batch", "image_hw": (32, 160),
    }
    premium = {
        "job_id": "u-prem", "seed": 99, "tenant": "tenant-p",
        "image_hw": (32, 64), "after_dispatches": 2,
    }
    r = run_chaos_xjob(
        seed=7, jobs=[spec], steps=5, premium=premium,
        drop_checkpoints=True,
    )
    assert r.resumes_recompute > 0
    totals = r.usage["totals"]
    assert totals["conserved"] is True
    assert totals["waste_ns"].get("preempt_recompute", 0) > 0
    # checkpoint/device resume re-runs nothing: no recompute waste
    ck = run_chaos_xjob(seed=7, jobs=[dict(spec)], steps=5,
                        premium=dict(premium))
    assert ck.resumes_checkpoint + ck.resumes_device > 0
    assert ck.usage["totals"]["waste_ns"].get("preempt_recompute", 0) == 0


# --------------------------------------------------------------------------
# replay stability (the CDT004 scope's point)
# --------------------------------------------------------------------------


def test_rollup_replay_stable_for_same_record_sequence():
    def feed(meter):
        meter.note_job_attrs("j1", "t-b", "batch")
        meter.note_job_attrs("j2", "t-a", "premium")
        for chips in (1, 2, 4):
            _dispatch(
                meter, chips=chips, elapsed=0.003,
                slots=[
                    {"job_id": "j2", "kind": SLOT_REAL},
                    {"job_id": "j1", "kind": SLOT_REAL},
                    {"job_id": "", "kind": SLOT_PADDING},
                ],
            )
        meter.note_tiles("worker", "j1", 2)
        meter.note_waste("worker", "speculation", 0.01, job_id="j2")

    a, b = UsageMeter(), UsageMeter()
    feed(a)
    feed(b)
    assert json.dumps(a.rollup(), sort_keys=False) == json.dumps(
        b.rollup(), sort_keys=False
    )
    assert json.dumps(a.snapshot("worker")) == json.dumps(
        b.snapshot("worker")
    )


# --------------------------------------------------------------------------
# adoption: delta merge + counter-reset clamp
# --------------------------------------------------------------------------


def _worker_snapshot(scale=1.0):
    return {
        "jobs": {
            "wj": {
                "chip_s": 2.0 * scale, "steps": 10 * scale,
                "tiles": 4 * scale, "waste_s": 0.5 * scale,
            }
        },
        "waste_s": {"padding": 0.5 * scale},
        "dispatch_chip_s": 2.5 * scale,
        "attributed_chip_s": 2.0 * scale,
        "overhead_s": 0.0,
        "dispatches": 5 * scale,
    }


def test_adoption_delta_and_counter_reset_clamp():
    agg = UsageAggregator(meter=UsageMeter(), ttl=10_000)
    assert agg.adopt("w1", _worker_snapshot(1.0))
    assert agg.adopt("w1", _worker_snapshot(2.0))  # grew: delta = +1x
    roll = agg.rollup()
    assert roll["totals"]["chip_s"] == pytest.approx(5.0)
    assert roll["jobs"]["wj"]["tiles"] == 8
    # RESTART: totals collapse below the last seen value — the smaller
    # snapshot adopts as a fresh baseline, never a negative delta
    assert agg.adopt("w1", _worker_snapshot(0.5))
    after_reset = agg.rollup()
    assert after_reset["totals"]["chip_s"] == pytest.approx(5.0 + 1.25)
    assert after_reset["jobs"]["wj"]["tiles"] == 8 + 2
    for stats in after_reset["tenants"].values():
        assert stats["chip_s"] >= 0
    # and growth from the new baseline counts normally again
    assert agg.adopt("w1", _worker_snapshot(1.0))
    assert agg.rollup()["totals"]["chip_s"] == pytest.approx(5.0 + 2.5)


def test_adoption_malformed_and_forget_worker():
    agg = UsageAggregator(meter=UsageMeter(), ttl=10_000)
    assert agg.adopt("w1", "not-a-dict") is False
    assert agg.adopt("w1", {"jobs": "nope", "waste_s": None}) is True
    assert agg.rollup()["totals"]["chip_s"] == 0.0
    agg.adopt("w1", _worker_snapshot(1.0))
    agg.forget_worker("w1")
    # baselines dropped: the same cumulative snapshot re-adopts in full
    # (a re-registered worker is a new counter lineage)
    agg.adopt("w1", _worker_snapshot(1.0))
    assert agg.rollup()["totals"]["chip_s"] == pytest.approx(5.0)


def test_master_attrs_resolve_adopted_jobs():
    meter = UsageMeter()
    meter.note_job_attrs("wj", "tenant-x", "premium")
    agg = UsageAggregator(meter=meter, ttl=10_000)
    agg.adopt("w1", _worker_snapshot(1.0))
    roll = agg.rollup()
    assert roll["tenants"]["tenant-x"]["chip_s"] == pytest.approx(2.0)
    assert roll["jobs"]["wj"]["lane"] == "premium"


def test_role_separation_prevents_cohosted_double_count():
    """A co-hosted worker's local records (role=worker) are excluded
    from the aggregator's local contribution — they arrive through its
    adopted snapshots instead, so one burn counts once."""
    meter = UsageMeter()
    _dispatch(meter, role="worker", elapsed=0.002)
    _dispatch(meter, role="master", elapsed=0.004)
    agg = UsageAggregator(meter=meter, ttl=10_000)
    roll = agg.rollup()
    assert roll["totals"]["chip_s"] == pytest.approx(0.004)
    agg.adopt("w1", meter.snapshot("worker"))
    assert agg.rollup()["totals"]["chip_s"] == pytest.approx(0.006)


# --------------------------------------------------------------------------
# eviction: flat memory under churn + the tenant series seam
# --------------------------------------------------------------------------


def test_tenant_churn_stays_bounded_and_fires_eviction_seam():
    from comfyui_distributed_tpu.telemetry.timeseries import SeriesStore

    clock = {"now": 1000.0}
    store = SeriesStore(clock=lambda: clock["now"])
    meter = UsageMeter(clock=lambda: clock["now"], max_keys=64)
    agg = UsageAggregator(
        meter=meter, store=store, clock=lambda: clock["now"], ttl=50.0,
        max_keys=64,
    )
    evicted: list[str] = []
    agg.on_evict_tenant = lambda tenant: (
        evicted.append(tenant), store.evict_label("tenant", tenant),
    )
    # churn 4x the cap of one-job tenants through meter + adoption
    for i in range(256):
        tenant = f"churn-{i}"
        job = f"cj-{i}"
        meter.note_job_attrs(job, tenant, "batch")
        _dispatch(
            meter, role="master", elapsed=0.001,
            slots=[{"job_id": job, "kind": SLOT_REAL}],
        )
        meter.note_tiles("master", job, 1)
        agg.adopt(f"w-{i % 8}", {
            "jobs": {job: {"chip_s": 0.1, "steps": 1, "tiles": 1,
                           "waste_s": 0.0}},
            "waste_s": {}, "dispatch_chip_s": 0.1,
            "attributed_chip_s": 0.1, "overhead_s": 0.0, "dispatches": 1,
        })
        agg.sample()
        clock["now"] += 60.0  # every entry idles past the 50 s TTL
    # bounded key maps: live jobs/tenants never exceed the cap
    assert len(meter._jobs.get("master", {})) <= 64
    assert len(agg._adopted_jobs) <= 64
    assert len(agg._cost) <= 66  # live window + default
    assert evicted, "idle tenants must depart through the seam"
    # departed tenants' series are evicted: the store stays bounded by
    # the cardinality cap, not by churn volume
    assert store.series_count() <= store.max_series * 3 + 8
    # totals stay conserved through all the folding
    assert meter.totals()["conserved"] is True


def test_meter_sweep_folds_idle_jobs_into_retired():
    clock = {"now": 0.0}
    meter = UsageMeter(clock=lambda: clock["now"])
    meter.note_job_attrs("old", "t", "batch")
    _dispatch(meter, slots=[{"job_id": "old", "kind": SLOT_REAL}])
    meter.note_tiles("worker", "old", 5)
    clock["now"] = 100.0
    assert meter.sweep(ttl_s=50.0) == ["old"]
    roll = meter.rollup()
    assert "old" not in roll["jobs"]
    # retired counters fold under the tenant/lane resolved AT eviction
    # time — the tenant view stays honest, not lumped into default
    assert roll["tenants"]["t"]["tiles"] == 5
    assert roll["lanes"]["batch"]["tiles"] == 5
    assert meter.totals()["conserved"] is True


def test_retired_fold_is_role_filtered():
    """A swept WORKER-role job must not leak into a master-filtered
    rollup — the role-separation rule survives eviction (a co-hosted
    worker's burn counts once, through its adopted snapshots)."""
    clock = {"now": 0.0}
    meter = UsageMeter(clock=lambda: clock["now"])
    meter.note_job_attrs("wj", "t-w", "")
    _dispatch(meter, role="worker", elapsed=0.1,
              slots=[{"job_id": "wj", "kind": SLOT_REAL}])
    _dispatch(meter, role="master", elapsed=0.05,
              slots=[{"job_id": "mj", "kind": SLOT_REAL}])
    clock["now"] = 100.0
    meter.sweep(ttl_s=50.0)
    master_roll = meter.rollup(roles=("master",))
    assert "t-w" not in master_roll["tenants"]
    assert master_roll["totals"]["chip_s"] == pytest.approx(0.05)
    # the all-roles view still carries the worker-role retired fold
    assert meter.rollup()["tenants"]["t-w"]["chip_s"] == pytest.approx(0.1)


def test_pair_totals_monotonic_across_eviction():
    """The scrape mirror deltas against pair_totals: TTL-sweeping a
    job must not shrink its (tenant, lane) pair."""
    clock = {"now": 0.0}
    meter = UsageMeter(clock=lambda: clock["now"])
    agg = UsageAggregator(meter=meter, clock=lambda: clock["now"], ttl=50.0)
    meter.note_job_attrs("pj", "t-p", "batch")
    agg.adopt("w1", {
        "jobs": {"pj": {"chip_s": 2.0, "steps": 10, "tiles": 4,
                        "waste_s": 0.0}},
        "waste_s": {}, "dispatch_chip_s": 2.0, "attributed_chip_s": 2.0,
        "overhead_s": 0.0, "dispatches": 1,
    })
    before = agg.pair_totals()[("t-p", "batch")]
    clock["now"] = 100.0
    agg.sample()  # sweeps the idle adopted job into the retired fold
    after = agg.pair_totals()[("t-p", "batch")]
    assert after["chip_s"] == pytest.approx(before["chip_s"])
    assert after["tiles"] == before["tiles"]
    # and the tenant rollup keeps the eviction-time resolution too
    assert agg.rollup()["tenants"]["t-p"]["chip_s"] == pytest.approx(2.0)


def test_worker_prev_baselines_pruned_with_job_churn():
    """The reset-clamp baseline map must track the worker's OWN
    (bounded) meter, not every job id it ever served."""
    agg = UsageAggregator(meter=UsageMeter(), ttl=10_000)
    for i in range(300):
        agg.adopt("w1", {
            "jobs": {f"churn-{i}": {"chip_s": 1.0, "steps": 1,
                                    "tiles": 1, "waste_s": 0.0}},
            "waste_s": {}, "dispatch_chip_s": 1.0,
            "attributed_chip_s": 1.0, "overhead_s": 0.0, "dispatches": 1,
        })
    job_paths = [
        p for p in agg._worker_prev["w1"] if p.startswith("job:")
    ]
    # only the latest snapshot's job survives (6 paths per job:
    # chip_s / waste_s / steps / tiles / cached_tiles / cached_s)
    assert len(job_paths) == 6, job_paths


# --------------------------------------------------------------------------
# the measured cost model + the DRR admission hook
# --------------------------------------------------------------------------


def _feed_cost(agg, meter, tenant, job, chip_s, tiles):
    meter.note_job_attrs(job, tenant, "batch")
    _dispatch(
        meter, role="master", elapsed=chip_s,
        slots=[{"job_id": job, "kind": SLOT_REAL}],
    )
    meter.note_tiles("master", job, tiles)


def test_cost_ratio_ewma_heavy_vs_light_tenant():
    meter = UsageMeter()
    agg = UsageAggregator(meter=meter, ttl=10_000)
    assert agg.cost_ratio("anyone") == 1.0  # cold model
    _feed_cost(agg, meter, "heavy", "jh", chip_s=0.9, tiles=1)
    _feed_cost(agg, meter, "light", "jl", chip_s=0.1, tiles=1)
    agg.sample()
    assert agg.cost_ratio("heavy") > 1.0
    assert agg.cost_ratio("light") < 1.0
    assert agg.cost_ratio("unknown") == 1.0
    # clamp: an extreme tenant cannot weigh more than 10x / less 0.1x
    assert 0.1 <= agg.cost_ratio("heavy") <= 10.0
    assert 0.1 <= agg.cost_ratio("light") <= 10.0


def test_scheduler_usage_cost_hook(monkeypatch):
    from comfyui_distributed_tpu.scheduler.control import SchedulerControl
    from comfyui_distributed_tpu.utils import constants

    control = SchedulerControl()
    payload = types.SimpleNamespace(
        tenant="heavy", lane=None, trace_id=None, deadline_s=None,
        extra={"estimated_tiles": 10},
    )
    # knob off: static cost regardless of the seam
    control.usage_cost = lambda tenant: 3.0
    monkeypatch.setattr(constants, "USAGE_COST_ENABLED", False)
    ticket = control.submit_payload(payload)
    assert ticket.cost == pytest.approx(10.0)
    control.queue.release(ticket)
    # knob on: measured ratio multiplies the estimate
    monkeypatch.setattr(constants, "USAGE_COST_ENABLED", True)
    ticket = control.submit_payload(payload)
    assert ticket.cost == pytest.approx(30.0)
    control.queue.release(ticket)
    # a raising/degenerate seam falls back to the static cost
    control.usage_cost = lambda tenant: (_ for _ in ()).throw(RuntimeError())
    ticket = control.submit_payload(payload)
    assert ticket.cost == pytest.approx(10.0)
    control.queue.release(ticket)
    control.usage_cost = lambda tenant: float("nan")
    ticket = control.submit_payload(payload)
    assert ticket.cost == pytest.approx(10.0)


# --------------------------------------------------------------------------
# store-side waste hooks (speculation loser, poison retry)
# --------------------------------------------------------------------------


def test_store_speculation_loser_and_poison_retry_charge_waste(server_loop):
    import asyncio

    from comfyui_distributed_tpu.jobs import JobStore
    from comfyui_distributed_tpu.telemetry.usage import get_usage_meter

    async def scenario():
        store = JobStore()
        await store.init_tile_job("uw-job", [0], tenant="t-w",
                                  lane="batch")
        # w1 claims the tile; the watchdog speculates it; w2 claims the
        # copy; both submit — w2's (second) result drops as the loser
        first = await store.pull_tasks("uw-job", "w1", timeout=0.1)
        assert first == [0]
        await store.speculate_in_flight("uw-job")
        second = await store.pull_tasks("uw-job", "w2", timeout=0.1)
        assert second == [0]
        assert await store.submit_result("uw-job", "w1", 0, {"p": 1})
        assert not await store.submit_result("uw-job", "w2", 0, {"p": 1})
        # quarantine-class requeue: w3 claims a fresh job's tile and
        # "dies" (breaker quarantine path)
        await store.init_tile_job("uw-job2", [0], tenant="t-w")
        third = await store.pull_tasks("uw-job2", "w3", timeout=0.1)
        assert third == [0]
        await store.requeue_worker_tasks("w3")

    asyncio.run_coroutine_threadsafe(
        scenario(), server_loop.loop
    ).result(timeout=30)
    totals = get_usage_meter().totals()
    assert totals["waste_ns"].get("speculation", 0) > 0
    assert totals["waste_ns"].get("poison_retry", 0) > 0
    # attrs landed from init_tile_job: the waste resolves to the tenant
    assert get_usage_meter().job_attrs("uw-job") == ("t-w", "batch")


# --------------------------------------------------------------------------
# snapshot wire format (v2) + fleet adoption
# --------------------------------------------------------------------------


def test_local_snapshot_v2_carries_usage_block():
    from comfyui_distributed_tpu.telemetry.fleet import (
        SNAPSHOT_VERSION,
        local_snapshot,
    )
    from comfyui_distributed_tpu.telemetry.usage import get_usage_meter

    _dispatch(get_usage_meter(), role="worker", elapsed=0.002)
    snap = local_snapshot(role="worker")
    # v3 added the profiling block; the usage block rides unchanged
    assert snap["v"] == SNAPSHOT_VERSION == 3
    assert snap["usage"]["dispatch_chip_s"] > 0
    assert snap["usage"]["dispatches"] == 1


def test_fleet_registry_gates_usage_on_version():
    from comfyui_distributed_tpu.telemetry.fleet import FleetRegistry

    registry = FleetRegistry()
    assert registry.usage is not None
    usage_block = _worker_snapshot(1.0)
    # v1 (old worker): accepted, usage ignored
    assert registry.note_snapshot(
        "w-old", {"v": 1, "tiles_total": 3, "usage": usage_block}
    )
    assert registry.usage.rollup()["totals"]["chip_s"] == 0.0
    # v2: usage adopted
    assert registry.note_snapshot(
        "w-new", {"v": 2, "tiles_total": 3, "usage": usage_block}
    )
    assert registry.usage.rollup()["totals"]["chip_s"] == pytest.approx(2.5)
    # unknown version: dropped entirely
    assert not registry.note_snapshot("w-future", {"v": 9})


# --------------------------------------------------------------------------
# the `cached` bucket (content-addressed tile cache settlements)
# --------------------------------------------------------------------------


def test_note_cached_outside_identity_and_in_cost_denominator():
    """Cache settlements ride OUTSIDE the dispatch conservation
    identity (no dispatch happened) but count in the job's finished
    tiles — the cost-model denominator — so a tenant whose jobs mostly
    hit the cache admits near-free under the DRR measured-cost hook."""
    meter = UsageMeter()
    agg = UsageAggregator(meter=meter, ttl=10_000)
    # identical real burn for both tenants...
    _feed_cost(agg, meter, "cold", "jc", chip_s=0.5, tiles=5)
    _feed_cost(agg, meter, "warm", "jw", chip_s=0.5, tiles=5)
    # ...but warm's job settles 45 more tiles straight from the cache
    meter.note_cached("master", "jw", 45)
    totals = meter.totals()
    assert totals["conserved"] is True  # identity untouched
    assert totals["cached_tiles"] == 45
    assert (
        totals["attributed_ns"]
        + totals["dispatch_waste_ns"]
        + totals["overhead_ns"]
        == totals["dispatch_chip_ns"]
    )
    roll = meter.rollup()
    assert roll["tenants"]["warm"]["cached_tiles"] == 45
    assert roll["jobs"]["jw"]["cached_tiles"] == 45
    assert roll["tenants"]["cold"]["cached_tiles"] == 0
    agg.sample()
    assert agg.cost_ratio("warm") < agg.cost_ratio("cold")


def test_note_cached_zero_or_negative_is_noop():
    meter = UsageMeter()
    meter.note_cached("master", "j", 0)
    meter.note_cached("master", "j", -3)
    assert meter.totals()["cached_tiles"] == 0
    assert meter.rollup()["jobs"] == {}


def test_cached_bucket_adopts_and_survives_retirement():
    """Worker-snapshot adoption deltas the cached bucket (version
    tolerant: a pre-cache snapshot reads as 0) and the retired fold
    keeps pair_totals monotonic across eviction."""
    clock = {"now": 0.0}
    meter = UsageMeter(clock=lambda: clock["now"])
    agg = UsageAggregator(meter=meter, clock=lambda: clock["now"], ttl=50.0)
    meter.note_job_attrs("cj", "t-c", "batch")
    snap = {
        "jobs": {"cj": {"chip_s": 1.0, "steps": 4, "tiles": 8,
                        "waste_s": 0.0, "cached_tiles": 6,
                        "cached_s": 0.001}},
        "waste_s": {}, "dispatch_chip_s": 1.0, "attributed_chip_s": 1.0,
        "overhead_s": 0.0, "dispatches": 1,
    }
    agg.adopt("w1", snap)
    assert agg.rollup()["tenants"]["t-c"]["cached_tiles"] == 6
    before = agg.pair_totals()[("t-c", "batch")]
    assert before["cached"] == 6
    clock["now"] = 100.0
    agg.sample()  # sweeps the idle adopted job into the retired fold
    after = agg.pair_totals()[("t-c", "batch")]
    assert after["cached"] == before["cached"]
    assert agg.rollup()["totals"]["cached_tiles"] == 6
    # a snapshot WITHOUT the cached fields adopts cleanly (delta 0)
    agg.adopt("w2", {
        "jobs": {"old": {"chip_s": 0.5, "steps": 1, "tiles": 1,
                         "waste_s": 0.0}},
        "waste_s": {}, "dispatch_chip_s": 0.5, "attributed_chip_s": 0.5,
        "overhead_s": 0.0, "dispatches": 1,
    })
    assert agg.rollup()["totals"]["cached_tiles"] == 6
