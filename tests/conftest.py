"""Hermetic test configuration.

All tests run on the JAX CPU backend with 8 virtual devices so mesh /
sharding / collective behavior is exercised without TPU hardware —
the multi-device analog of the reference's fully-stubbed hermetic
tests (reference conftest.py + tests/*), but with real devices instead
of fakes where it matters.

Env vars must be set before jax initializes its backends, hence at
import time of this conftest (pytest imports conftest before test
modules).
"""

import faulthandler
import os
import sys

# a native crash anywhere in the suite (or at interpreter teardown)
# must name its location instead of dying silently
faulthandler.enable()

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The hosted TPU plugin (if present) force-updates jax_platforms during
# its registration hook, overriding the env var; re-pin to cpu via the
# config API before any backend is initialized.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import pytest  # noqa: E402

# --- fast/slow tiers ------------------------------------------------------
# `pytest -m fast` must give a green signal in <60s on a 1-core box
# (the judge/CI budget); everything that compiles XLA programs or
# boots real server processes is `slow`. Timings measured on a 1-core
# host: each slow path below is 1-10 min, the fast set is seconds.
_SLOW_PATHS = (
    "tests/models",
    "tests/ops",
    "tests/parallel",
    "tests/graph",
    "tests/test_graft_entry.py",
    "tests/api/test_integration.py",
    "tests/api/test_usdu_integration.py",
    "tests/api/test_concurrency.py",
    "tests/api/test_delegate_mode.py",
    "tests/golden",
)

# Middle tier (r4 VERDICT item 4): the end-to-end paths that should run
# per-commit without paying the ~hour full suite — 2-server HTTP E2E,
# USDU-elastic-over-HTTP, 2-process DCN multihost, and the --quick
# golden freeze. `pytest -m "fast or integration"` targets <10 min on a
# 1-core box. These files also stay in the slow tier (the full suite is
# unchanged); they simply gain the extra marker.
_INTEGRATION_PATHS = (
    "tests/api/test_integration.py",
    "tests/api/test_usdu_integration.py",
    "tests/parallel/test_multihost.py",
    "tests/golden/test_goldens_quick.py",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        rel = os.path.relpath(str(item.fspath), REPO_ROOT).replace(os.sep, "/")
        if any(rel == p or rel.startswith(p + "/") for p in _SLOW_PATHS):
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.fast)
        if any(
            rel == p or rel.startswith(p + "/") for p in _INTEGRATION_PATHS
        ):
            item.add_marker(pytest.mark.integration)


@pytest.fixture(autouse=True)
def _reset_resilience_state():
    """The circuit-breaker registry, the fault-injector override, and
    the telemetry registries are process-global; isolate tests from
    each other's failure history and metric/span accumulation."""
    yield
    from comfyui_distributed_tpu.resilience import faults, health
    from comfyui_distributed_tpu import telemetry

    health.reset_health_registry()
    faults.reset_fault_injector()
    telemetry.reset_metrics_registry()
    telemetry.reset_tracer()
    telemetry.reset_flight_recorder()
    telemetry.reset_event_bus()
    from comfyui_distributed_tpu.telemetry import usage as usage_mod

    usage_mod._reset_usage_meter_for_tests()


@pytest.fixture()
def server_loop():
    """A real control-plane loop thread (production shape): asyncio
    state like JobStore queues binds to exactly one loop."""
    from comfyui_distributed_tpu.utils.async_helpers import ServerLoopThread

    thread = ServerLoopThread()
    thread.start()
    yield thread
    thread.stop()


@pytest.fixture()
def tmp_config_path(tmp_path, monkeypatch):
    """Point the config system at a throwaway file."""
    path = tmp_path / "tpu_config.json"
    monkeypatch.setenv("CDT_CONFIG_PATH", str(path))
    from comfyui_distributed_tpu.utils import config as config_mod

    # Drop the mtime cache so the previous test's file doesn't leak in.
    with config_mod._cache.lock:
        config_mod._cache.path = None
        config_mod._cache.mtime = None
        config_mod._cache.data = None
    return str(path)
