"""docs/nodes.md must match the live node registry (generated doc —
the drift guard that keeps the node reference honest). Runs the
generator in a SUBPROCESS: other tests register throwaway node classes
into the in-process registry, which would pollute an in-process
comparison."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_node_docs_current():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "gen_node_docs.py"),
         "--check"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300,
    )
    assert proc.returncode == 0, (
        f"docs/nodes.md is stale; run scripts/gen_node_docs.py\n"
        f"{proc.stdout}{proc.stderr}"
    )
