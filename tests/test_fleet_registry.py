"""FleetRegistry (telemetry/fleet.py): snapshot versioning, rate
derivation, rollup semantics, TTL/forget eviction seams, the
1024-churning-workers memory bound, master-side sampling into the SLO
engine, and worker-side snapshot production."""

import pytest

from comfyui_distributed_tpu.telemetry import instruments
from comfyui_distributed_tpu.telemetry.fleet import (
    MAX_TRACKED_WORKERS,
    SNAPSHOT_VERSION,
    FleetRegistry,
    local_snapshot,
)
from comfyui_distributed_tpu.telemetry.timeseries import SeriesStore

pytestmark = pytest.mark.fast


class Clock:
    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


def snap(tiles=0.0, devices=1, **extra):
    out = {"v": SNAPSHOT_VERSION, "tiles_total": tiles, "devices": devices}
    out.update(extra)
    return out


def make_registry(clock, **kwargs):
    kwargs.setdefault("store", SeriesStore(clock=clock))
    kwargs.setdefault("ttl", 60.0)
    return FleetRegistry(clock=clock, **kwargs)


def test_snapshot_version_gate():
    clock = Clock()
    registry = make_registry(clock)
    assert registry.note_snapshot("w1", snap()) is True
    assert registry.note_snapshot("w2", {"v": 99}) is False
    assert registry.note_snapshot("w3", "not-a-dict") is False
    assert registry.worker_ids() == ["w1"]
    counter = instruments.fleet_snapshots_total()
    assert counter.value(outcome="accepted") == 1
    assert counter.value(outcome="bad_version") == 1
    assert counter.value(outcome="malformed") == 1


def test_rate_derived_from_successive_snapshots_on_master_clock():
    clock = Clock()
    registry = make_registry(clock)
    registry.note_snapshot("w1", snap(tiles=10))
    clock.advance(10.0)
    registry.note_snapshot("w1", snap(tiles=30))
    detail = registry.status()["workers"]["w1"]
    assert detail["tiles_per_s"] == pytest.approx(2.0)
    # a reset counter (worker restart) must not produce negative rates
    clock.advance(10.0)
    registry.note_snapshot("w1", snap(tiles=0))
    assert registry.status()["workers"]["w1"]["tiles_per_s"] >= 0.0


def test_rollup_sums_and_max_envelopes():
    clock = Clock()
    registry = make_registry(clock)
    registry.note_snapshot("w1", snap(
        tiles=0, devices=4, inflight=1,
        stages={"sample": {"p50": 0.1, "p95": 0.5, "count": 10}},
        jax={"compiles": 2, "cache_hits": 3, "cache_misses": 1},
        mem={"hbm_peak_bytes": 100, "rss_bytes": 50},
    ))
    registry.note_snapshot("w2", snap(
        tiles=0, devices=2, inflight=2,
        stages={"sample": {"p50": 0.2, "p95": 0.9, "count": 5}},
        jax={"compiles": 1, "cache_hits": 0, "cache_misses": 4},
        mem={"hbm_peak_bytes": 300, "rss_bytes": 20},
    ))
    rollup = registry.rollup()
    assert rollup["workers"] == 2
    assert rollup["devices"] == 6
    assert rollup["inflight"] == 3
    assert rollup["stages"]["sample"]["p95"] == 0.9  # max envelope
    assert rollup["stages"]["sample"]["count"] == 15
    assert rollup["jax"]["compiles"] == 3
    assert rollup["mem"]["hbm_peak_bytes"] == 300
    assert rollup["mem"]["rss_max_bytes"] == 50


def test_ttl_sweep_evicts_departed_worker_and_its_series():
    clock = Clock()
    registry = make_registry(clock, ttl=30.0)
    registry.note_snapshot("w1", snap(tiles=1))
    registry.note_snapshot("w2", snap(tiles=1))
    clock.advance(20.0)
    registry.note_snapshot("w2", snap(tiles=2))
    clock.advance(20.0)  # w1 last seen 40s ago, w2 20s ago
    assert registry.sweep() == ["w1"]
    assert registry.worker_ids() == ["w2"]
    assert registry.store.label_values(
        "fleet_worker_tiles_per_s", "worker_id"
    ) == ["w2"]
    assert instruments.fleet_evictions_total().value(reason="ttl") == 1


def test_forget_worker_seam_drops_series():
    clock = Clock()
    registry = make_registry(clock)
    registry.note_snapshot("w1", snap(tiles=5))
    registry.forget_worker("w1")
    assert registry.worker_ids() == []
    assert registry.store.series_count() == 0
    assert instruments.fleet_evictions_total().value(reason="forgotten") == 1


def test_placement_forget_hook_reaches_the_fleet_registry():
    from comfyui_distributed_tpu.scheduler.placement import PlacementPolicy

    clock = Clock()
    registry = make_registry(clock)
    policy = PlacementPolicy()
    policy.on_forget = registry.forget_worker
    registry.note_snapshot("w1", snap(tiles=5))
    policy.set_capacity("w1", 2)
    policy.forget("w1")
    assert registry.worker_ids() == []
    assert registry.store.series_count() == 0


def test_health_registry_reset_hook_reaches_the_fleet_registry():
    from comfyui_distributed_tpu.resilience.health import HealthRegistry

    clock = Clock()
    registry = make_registry(clock)
    health = HealthRegistry()
    health.on_forget = registry.forget_worker
    registry.note_snapshot("w1", snap(tiles=5))
    health.record_failure("w1")
    health.reset("w1")
    assert registry.worker_ids() == []
    assert registry.store.series_count() == 0


def test_churning_worker_ids_never_grow_master_memory():
    """The PR 8 MAX_TRACKED_WORKERS idiom, regression-tested for the
    fleet plane: 4x the bound in churning fake workers, each
    snapshotting once, must neither exceed the tracking bound nor grow
    the series store past its cardinality caps."""
    clock = Clock()
    store = SeriesStore(clock=clock)
    registry = make_registry(clock, store=store)
    for wave in range(4):
        for i in range(MAX_TRACKED_WORKERS):
            registry.note_snapshot(
                f"churn-{wave}-{i}", snap(tiles=float(i))
            )
            clock.advance(0.001)
    assert len(registry.worker_ids()) <= MAX_TRACKED_WORKERS
    # per-name series stay under the CDT_METRIC_MAX_SERIES cap
    by_name = store.counts_by_name()
    assert by_name, "no series recorded at all"
    assert all(count <= store.max_series for count in by_name.values())
    # churn evicted the earlier waves (capacity reason)
    assert instruments.fleet_evictions_total().value(reason="capacity") > 0
    # and a second full wave leaves the footprint FLAT (no leak)
    before = (len(registry.worker_ids()), store.series_count())
    for i in range(MAX_TRACKED_WORKERS):
        registry.note_snapshot(f"churn-final-{i}", snap(tiles=float(i)))
        clock.advance(0.001)
    after = (len(registry.worker_ids()), store.series_count())
    assert after[0] <= before[0]
    assert after[1] <= before[1]


def test_master_sampling_feeds_series_and_slo_counters():
    from comfyui_distributed_tpu.scheduler import SchedulerControl
    from comfyui_distributed_tpu.telemetry.slo import SLOEngine

    clock = Clock()
    registry = make_registry(clock)
    slo = SLOEngine(store=SeriesStore(clock=clock), clock=clock)
    scheduler = SchedulerControl()
    scheduler.brownout.note_queue_wait(1.5)
    scheduler.queue.totals["admitted"] = 40
    scheduler.queue.totals["rejected_full"] = 4
    scheduler.queue.totals["rejected_draining"] = 1
    scheduler.brownout.shed_counts["batch"] = 10
    registry.bind_master(scheduler=scheduler, slo=slo)
    rollup = registry.sample()
    assert registry.store.latest("fleet_queue_wait_p95") == 1.5
    assert registry.store.latest("fleet_shed_total") == 10.0
    assert rollup["workers"] == 0
    # availability adopted the cumulative counters: EVERY refused
    # admission (shed + saturation/drain rejections) counts as bad
    assert slo.store.latest("slo_bad_total", slo="availability") == 15.0
    assert slo.store.latest("slo_total_total", slo="availability") == 55.0


def test_status_windowed_history_and_worker_scope():
    clock = Clock()
    registry = make_registry(clock)
    for i in range(5):
        registry.note_snapshot("w1", snap(tiles=float(i * 10)))
        registry.note_snapshot("w2", snap(tiles=float(i)))
        registry.sample()
        clock.advance(10.0)
    status = registry.status(since_s=120.0)
    assert "fleet_tiles_per_s" in status["history"]
    assert set(status["history"]["workers"]) == {"w1", "w2"}
    scoped = registry.status(since_s=120.0, worker="w1")
    assert list(scoped["workers"]) == ["w1"]
    assert list(scoped["history"]["workers"]) == ["w1"]


def test_local_snapshot_reads_real_instruments():
    instruments.tile_stage_seconds().observe(0.2, stage="sample", role="worker")
    instruments.tile_stage_seconds().observe(0.4, stage="sample", role="worker")
    instruments.tile_stage_seconds().observe(9.9, stage="blend", role="master")
    instruments.tiles_processed_total().inc(2, role="worker")
    instruments.pipeline_inflight().set(1, role="worker")
    snapshot = local_snapshot(role="worker")
    assert snapshot["v"] == SNAPSHOT_VERSION
    assert snapshot["tiles_total"] == 2
    assert snapshot["inflight"] == 1
    sample = snapshot["stages"]["sample"]
    assert sample["count"] == 2
    assert sample["p95"] >= sample["p50"] > 0
    # the master-role observation must not leak into a worker snapshot
    assert "blend" not in snapshot["stages"]
    assert set(snapshot["jax"]) == {
        "compiles", "compile_time_s", "cache_hits", "cache_misses"
    }
    assert "hbm_peak_bytes" in snapshot["mem"]
    # round-trips through the registry
    clock = Clock()
    registry = make_registry(clock)
    assert registry.note_snapshot("w1", snapshot) is True
