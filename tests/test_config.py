"""Config system: defaults merge, atomic save, mtime cache, transaction.

Mirrors the coverage of reference tests/test_config.py against our
re-designed implementation.
"""

import asyncio
import json
import os

from comfyui_distributed_tpu.utils import config as cfg


def test_defaults_when_missing(tmp_config_path):
    loaded = cfg.load_config()
    assert loaded["settings"]["debug"] is False
    assert loaded["workers"] == []
    assert loaded["mesh"]["axes"]["data"] == -1


def test_merge_preserves_unknown_keys(tmp_config_path):
    with open(tmp_config_path, "w") as fh:
        json.dump(
            {
                "settings": {"debug": True, "my_custom_flag": 7},
                "frontier": {"x": 1},
            },
            fh,
        )
    loaded = cfg.load_config()
    assert loaded["settings"]["debug"] is True
    assert loaded["settings"]["my_custom_flag"] == 7
    assert loaded["frontier"] == {"x": 1}
    # defaults still present
    assert "worker_timeout_seconds" in loaded["settings"]


def test_save_and_reload_roundtrip(tmp_config_path):
    config = cfg.load_config()
    config["workers"].append(
        {"id": "w0", "name": "chip0", "type": "mesh", "tpu_chips": [1], "enabled": True}
    )
    cfg.save_config(config)
    # no tmp litter
    directory = os.path.dirname(tmp_config_path)
    assert not [f for f in os.listdir(directory) if f.endswith(".tmp")]
    again = cfg.load_config()
    assert again["workers"][0]["id"] == "w0"
    assert cfg.get_enabled_workers()[0]["name"] == "chip0"


def test_mtime_cache_returns_copy(tmp_config_path):
    first = cfg.load_config()
    first["settings"]["debug"] = True  # mutate the returned copy
    second = cfg.load_config()
    assert second["settings"]["debug"] is False


def test_transaction_persists_only_on_change(tmp_config_path):
    async def scenario():
        async with cfg.config_transaction() as config:
            config["settings"]["debug"] = True
        assert os.path.exists(tmp_config_path)
        mtime = os.path.getmtime(tmp_config_path)
        async with cfg.config_transaction() as config:
            pass  # no mutation → no write
        assert os.path.getmtime(tmp_config_path) == mtime

    asyncio.run(scenario())


def test_locked_config_shares_transaction_mutex(tmp_config_path):
    """Sync writers (worker PID persistence) and the async transaction
    path must exclude each other — same mutex, same
    persist-only-on-change semantics."""
    import threading

    with cfg.locked_config() as config:
        config["managed_processes"] = {"w1": {"pid": 1}}
    assert cfg.load_config()["managed_processes"] == {"w1": {"pid": 1}}
    mtime = os.path.getmtime(tmp_config_path)
    with cfg.locked_config():
        pass  # no mutation -> no write
    assert os.path.getmtime(tmp_config_path) == mtime

    # mutual exclusion with the async transaction: sync side holds the
    # mutex; the async transaction must not complete until it releases
    entered = threading.Event()
    release = threading.Event()
    order = []

    def sync_side():
        with cfg.locked_config() as config:
            entered.set()
            release.wait(timeout=5)
            config["settings"]["debug"] = True
            order.append("sync")

    thread = threading.Thread(target=sync_side)
    thread.start()
    entered.wait(timeout=5)

    async def async_side():
        async with cfg.config_transaction() as config:
            order.append("async")
            config["settings"]["debug"] = False

    import concurrent.futures

    with concurrent.futures.ThreadPoolExecutor(1) as pool:
        fut = pool.submit(asyncio.run, async_side())
        release.set()
        fut.result(timeout=10)
    thread.join(timeout=5)
    assert order == ["sync", "async"], "transaction ran inside the sync lock"
    assert cfg.load_config()["settings"]["debug"] is False


def test_worker_timeout_fallbacks(tmp_config_path):
    assert cfg.get_worker_timeout_seconds() == 60.0
    config = cfg.load_config()
    config["settings"]["worker_timeout_seconds"] = "nonsense"
    cfg.save_config(config)
    assert cfg.get_worker_timeout_seconds() == 60.0
    config["settings"]["worker_timeout_seconds"] = -5
    cfg.save_config(config)
    assert cfg.get_worker_timeout_seconds() == 60.0
    config["settings"]["worker_timeout_seconds"] = 120
    cfg.save_config(config)
    assert cfg.get_worker_timeout_seconds() == 120.0
