"""cdt-lint checker tests: per-checker fixture TP/TN/noqa coverage plus
the baseline-drift gate (a fresh scan of the repo must match the
committed baseline — new findings or stale entries fail tier-1)."""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys

import pytest

from tools.cdtlint import Baseline, all_checkers, run_lint
from tools.cdtlint.baseline import DEFAULT_BASELINE_PATH
from tools.cdtlint.core import parse_noqa

pytestmark = pytest.mark.fast

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")

# CDT004 only fires on the determinism-sensitive module list, so its
# fixtures mount at one of those paths inside the synthetic tree.
DETERMINISM_MOUNT = "comfyui_distributed_tpu/ops/tiles.py"

# CDT007 only fires on the device-resident hot-path modules.
HOT_PATH_MOUNT = "comfyui_distributed_tpu/graph/tile_pipeline.py"


def lint_fixture(tmp_path, mapping: dict[str, str], select: set[str]):
    """Copy fixture files into a synthetic tree and lint it."""
    for dest, fixture in mapping.items():
        target = tmp_path / dest
        target.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(os.path.join(FIXTURES, fixture), target)
    return run_lint(str(tmp_path), paths=sorted(mapping), select=select)


# --------------------------------------------------------------------------
# CDT001 blocking-call-in-async
# --------------------------------------------------------------------------

def test_cdt001_true_positives(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt001_tp.py"}, {"CDT001"})
    assert all(f.code == "CDT001" for f in result.findings)
    messages = "\n".join(f.message for f in result.findings)
    assert "time.sleep" in messages
    assert "requests.get" in messages
    assert "subprocess.run" in messages
    assert ".acquire()" in messages
    assert "`open(...)`" in messages
    assert len(result.findings) == 5


def test_cdt001_true_negatives(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt001_tn.py"}, {"CDT001"})
    assert result.findings == []


def test_cdt001_noqa_suppression(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt001_noqa.py"}, {"CDT001"})
    assert result.findings == []
    assert len(result.suppressed) == 2


# --------------------------------------------------------------------------
# CDT002 lock-discipline
# --------------------------------------------------------------------------

def test_cdt002_true_positives(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt002_tp.py"}, {"CDT002"})
    assert all(f.code == "CDT002" for f in result.findings)
    messages = "\n".join(f.message for f in result.findings)
    assert "held across `await`" in messages
    assert "sync `with" in messages
    assert ".acquire()" in messages
    assert len(result.findings) == 4


def test_cdt002_true_negatives(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt002_tn.py"}, {"CDT002"})
    assert result.findings == []


def test_cdt002_noqa_suppression(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt002_noqa.py"}, {"CDT002"})
    assert result.findings == []
    assert len(result.suppressed) == 1


# --------------------------------------------------------------------------
# CDT003 jax-tracing-hygiene
# --------------------------------------------------------------------------

def test_cdt003_true_positives(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt003_tp.py"}, {"CDT003"})
    assert all(f.code == "CDT003" for f in result.findings)
    messages = "\n".join(f.message for f in result.findings)
    assert "np.asarray" in messages
    assert "traced parameter" in messages  # float() on non-static param
    assert "print" in messages
    assert "block_until_ready" in messages
    assert "*.item" in messages
    assert "random.random" in messages
    assert "time.time" in messages
    assert "*.tolist" in messages  # via jax.vmap(referenced_by_vmap)
    assert len(result.findings) == 8


def test_cdt003_true_negatives(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt003_tn.py"}, {"CDT003"})
    assert result.findings == []


def test_cdt003_noqa_suppression(tmp_path):
    result = lint_fixture(tmp_path, {"pkg/mod.py": "cdt003_noqa.py"}, {"CDT003"})
    assert result.findings == []
    assert len(result.suppressed) == 1


# --------------------------------------------------------------------------
# CDT004 determinism
# --------------------------------------------------------------------------

def test_cdt004_true_positives(tmp_path):
    result = lint_fixture(tmp_path, {DETERMINISM_MOUNT: "cdt004_tp.py"}, {"CDT004"})
    assert all(f.code == "CDT004" for f in result.findings)
    messages = "\n".join(f.message for f in result.findings)
    assert "iterates a set" in messages
    assert "os.listdir" in messages
    assert "glob.glob" in messages
    assert "global RNG" in messages
    assert "wall-clock" in messages
    assert len(result.findings) == 6


def test_cdt004_outside_sensitive_modules_is_silent(tmp_path):
    # same hazards mounted OUTSIDE the determinism module list: no findings
    result = lint_fixture(tmp_path, {"pkg/free_module.py": "cdt004_tp.py"}, {"CDT004"})
    assert result.findings == []


def test_cdt004_true_negatives(tmp_path):
    result = lint_fixture(tmp_path, {DETERMINISM_MOUNT: "cdt004_tn.py"}, {"CDT004"})
    assert result.findings == []


def test_cdt004_noqa_suppression(tmp_path):
    result = lint_fixture(tmp_path, {DETERMINISM_MOUNT: "cdt004_noqa.py"}, {"CDT004"})
    assert result.findings == []
    assert len(result.suppressed) == 1


# --------------------------------------------------------------------------
# CDT005 registry-consistency (project-level)
# --------------------------------------------------------------------------

def _mount_cdt005(tmp_path, with_doc: bool = True, extra: dict[str, str] | None = None):
    mapping = {
        "comfyui_distributed_tpu/utils/knob_registry.py": "cdt005_registry.py",
        "comfyui_distributed_tpu/mod.py": "cdt005_code.py",
    }
    mapping.update(extra or {})
    if with_doc:
        doc = tmp_path / "docs" / "configuration.md"
        doc.parent.mkdir(parents=True, exist_ok=True)
        doc.write_text("| `CDT_FIXTURE_DOCUMENTED` | `1` | a knob |\n")
    return lint_fixture(tmp_path, mapping, {"CDT005"})


def test_cdt005_true_positives(tmp_path):
    result = _mount_cdt005(tmp_path)
    assert all(f.code == "CDT005" for f in result.findings)
    messages = "\n".join(f.message for f in result.findings)
    # undeclared read, stale declaration, three metric-name violations
    assert "CDT_FIXTURE_UNDECLARED" in messages
    assert "CDT_FIXTURE_STALE" in messages
    assert "`fixture_events_total`" in messages  # missing cdt_ prefix
    assert "`cdt_fixture_events`" in messages  # counter without _total
    assert "`cdt_fixture_depth_total`" in messages  # gauge with _total
    assert len(result.findings) == 5


def test_cdt005_true_negative_documented_knob(tmp_path):
    result = _mount_cdt005(tmp_path)
    # the declared+read+documented knob produces no finding
    assert "CDT_FIXTURE_DOCUMENTED" not in "\n".join(f.message for f in result.findings)


def test_cdt005_missing_doc_is_a_finding(tmp_path):
    result = _mount_cdt005(tmp_path, with_doc=False)
    assert any("does not exist" in f.message for f in result.findings)


def test_cdt005_noqa_suppression(tmp_path):
    result = _mount_cdt005(
        tmp_path, extra={"comfyui_distributed_tpu/transitional.py": "cdt005_noqa.py"}
    )
    assert any("CDT_FIXTURE_TRANSITIONAL" in f.message for f in result.suppressed)
    assert not any("CDT_FIXTURE_TRANSITIONAL" in f.message for f in result.findings)


# --------------------------------------------------------------------------
# CDT006 instrument-registry (project-level)
# --------------------------------------------------------------------------

def _mount_cdt006(tmp_path, with_doc: bool = True, doc_text: str | None = None,
                  extra: dict[str, str] | None = None):
    mapping = {
        "comfyui_distributed_tpu/telemetry/instruments.py": "cdt006_instruments.py",
        "comfyui_distributed_tpu/mod.py": "cdt006_inline.py",
    }
    mapping.update(extra or {})
    if with_doc:
        doc = tmp_path / "docs" / "observability.md"
        doc.parent.mkdir(parents=True, exist_ok=True)
        doc.write_text(
            doc_text
            if doc_text is not None
            else "| `cdt_fixture_ok_total` | counter | — | documented |\n"
                 "| `cdt_fixture_ghost_total` | counter | — | undeclared |\n"
        )
    return lint_fixture(tmp_path, mapping, {"CDT006"})


def test_cdt006_true_positives(tmp_path):
    result = _mount_cdt006(tmp_path)
    assert all(f.code == "CDT006" for f in result.findings)
    messages = "\n".join(f.message for f in result.findings)
    # undocumented declaration, doc ghost, inline declaration
    assert "`cdt_fixture_undocumented_total`" in messages
    assert "`cdt_fixture_ghost_total`" in messages
    assert "`cdt_fixture_inline`" in messages
    assert len(result.findings) == 3


def test_cdt006_true_negative_documented_metric(tmp_path):
    result = _mount_cdt006(tmp_path)
    assert "`cdt_fixture_ok_total`" not in "\n".join(
        f.message for f in result.findings
    )


def test_cdt006_histogram_suffixes_resolve_to_base(tmp_path):
    # the doc mentioning cdt_fixture_ok_total_count (exposition suffix)
    # must neither create a ghost nor hide the base declaration
    result = _mount_cdt006(
        tmp_path,
        doc_text="`cdt_fixture_ok_total_count` and "
                 "`cdt_fixture_undocumented_total` rows\n",
    )
    messages = "\n".join(f.message for f in result.findings)
    assert "`cdt_fixture_ok_total`" not in messages
    assert "cdt_fixture_ok_total_count" not in messages


def test_cdt006_missing_doc_is_a_finding(tmp_path):
    result = _mount_cdt006(tmp_path, with_doc=False)
    assert any("does not exist" in f.message for f in result.findings)


def test_cdt006_known_extra_not_a_ghost(tmp_path):
    # the registry-internal overflow counter is declared outside
    # instruments.py by construction; the doc may mention it freely
    result = _mount_cdt006(
        tmp_path,
        doc_text="| `cdt_fixture_ok_total` | counter |\n"
                 "| `cdt_metric_series_overflow_total` | counter |\n",
    )
    assert "cdt_metric_series_overflow_total" not in "\n".join(
        f.message for f in result.findings
    )


def test_cdt006_noqa_suppression(tmp_path):
    result = _mount_cdt006(
        tmp_path,
        extra={"comfyui_distributed_tpu/transitional.py": "cdt006_noqa.py"},
    )
    assert any(
        "cdt_fixture_transitional" in f.message for f in result.suppressed
    )
    assert not any(
        "cdt_fixture_transitional" in f.message for f in result.findings
    )


# --------------------------------------------------------------------------
# CDT007 host-sync-hot-path
# --------------------------------------------------------------------------

def test_cdt007_true_positives(tmp_path):
    result = lint_fixture(tmp_path, {HOT_PATH_MOUNT: "cdt007_tp.py"}, {"CDT007"})
    assert all(f.code == "CDT007" for f in result.findings)
    messages = "\n".join(f.message for f in result.findings)
    assert "`np.asarray(...)`" in messages
    assert "`np.ascontiguousarray(...)`" in messages
    assert "`np.stack(...)`" in messages
    assert "`jax.device_get(...)`" in messages
    assert "block_until_ready" in messages
    assert "`ensure_numpy(...)`" in messages
    # asarray + ascontiguousarray + stack + device_get, two sync
    # barriers (method + functional form), one materialization helper
    assert len(result.findings) == 7


def test_cdt007_outside_hot_path_is_silent(tmp_path):
    # same host pulls mounted OUTSIDE the hot-path module list: silent
    result = lint_fixture(tmp_path, {"pkg/free_module.py": "cdt007_tp.py"}, {"CDT007"})
    assert result.findings == []


def test_cdt007_true_negatives(tmp_path):
    result = lint_fixture(tmp_path, {HOT_PATH_MOUNT: "cdt007_tn.py"}, {"CDT007"})
    assert result.findings == []


def test_cdt007_noqa_suppression(tmp_path):
    result = lint_fixture(tmp_path, {HOT_PATH_MOUNT: "cdt007_noqa.py"}, {"CDT007"})
    assert result.findings == []
    assert len(result.suppressed) == 1


# --------------------------------------------------------------------------
# framework: noqa parsing, baseline drift, CLI
# --------------------------------------------------------------------------

def test_parse_noqa_forms():
    lines = [
        "x = 1  # cdt: noqa",
        "y = 2  # cdt: noqa[CDT001]",
        "z = 3  # cdt: noqa[CDT001, CDT004]",
        "w = 4  # unrelated comment",
        "v = 5  # noqa (plain ruff-style noqa is NOT a cdt suppression)",
    ]
    parsed = parse_noqa(lines)
    assert parsed[1] is None  # blanket
    assert parsed[2] == frozenset({"CDT001"})
    assert parsed[3] == frozenset({"CDT001", "CDT004"})
    assert 4 not in parsed
    assert 5 not in parsed


def test_every_checker_registered_has_fixture_coverage():
    codes = set(all_checkers())
    assert codes == {
        "CDT001", "CDT002", "CDT003", "CDT004", "CDT005", "CDT006", "CDT007",
    }
    for code in codes:
        n = code[-3:].lstrip("0")
        named = [f for f in os.listdir(FIXTURES) if f.startswith(f"cdt00{n}")]
        assert named, f"no fixtures for {code}"


def test_committed_baseline_matches_fresh_scan():
    """Drift gate: the repo must lint clean against the committed
    baseline — any new finding, stale entry, or parse error fails."""
    baseline = Baseline.load(os.path.join(REPO_ROOT, DEFAULT_BASELINE_PATH))
    result = run_lint(REPO_ROOT, baseline=baseline)
    assert result.parse_errors == []
    assert result.stale_baseline == []
    assert [f.render() for f in result.findings] == []
    # every grandfathered entry must carry a real justification
    for fp, entry in baseline.entries.items():
        assert entry.get("justification") and "TODO" not in entry["justification"], (
            f"baseline entry {fp} ({entry.get('code')} at {entry.get('path')}) "
            "has no justification"
        )


def _run_cli(*args: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "cdt_lint.py"), *args],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )


def test_cli_clean_run_exits_zero():
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_json_format():
    proc = _run_cli("--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] is True
    assert report["files_scanned"] > 100


def test_cli_list_checkers():
    proc = _run_cli("--list-checkers")
    assert proc.returncode == 0
    for code in (
        "CDT001", "CDT002", "CDT003", "CDT004", "CDT005", "CDT006", "CDT007",
    ):
        assert code in proc.stdout


def test_cli_findings_exit_one_and_update_baseline_policy(tmp_path):
    fixture_rel = os.path.join("tests", "lint", "fixtures", "cdt001_tp.py")
    empty = tmp_path / "baseline.json"
    # findings without a baseline: exit 1
    proc = _run_cli(fixture_rel, "--select", "CDT001", "--baseline", str(empty))
    assert proc.returncode == 1
    assert "CDT001" in proc.stdout
    # shrink-only policy: --update-baseline refuses to grow without --force
    proc = _run_cli(
        fixture_rel, "--select", "CDT001", "--baseline", str(empty), "--update-baseline"
    )
    assert proc.returncode == 2
    assert "refusing" in proc.stderr
    # --force writes it; the subsequent scan is green against it
    proc = _run_cli(
        fixture_rel, "--select", "CDT001", "--baseline", str(empty),
        "--update-baseline", "--force",
    )
    assert proc.returncode == 0
    data = json.loads(empty.read_text())
    assert len(data["entries"]) == 5
    proc = _run_cli(fixture_rel, "--select", "CDT001", "--baseline", str(empty))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_partial_scan_does_not_report_out_of_scope_baseline_as_stale(tmp_path):
    """A scan restricted to a subset of paths/checkers must not flag
    baseline entries it could never have re-produced as stale."""
    for name in ("a.py", "b.py"):
        target = tmp_path / "pkg" / name
        target.parent.mkdir(parents=True, exist_ok=True)
        shutil.copyfile(os.path.join(FIXTURES, "cdt001_tp.py"), target)
    # baseline everything in b.py
    full = run_lint(str(tmp_path), paths=["pkg/b.py"], select={"CDT001"})
    baseline = Baseline(path=str(tmp_path / "baseline.json"))
    from tools.cdtlint.runner import compute_fingerprints

    baseline.entries = compute_fingerprints(str(tmp_path), full.findings)
    # path-scoped scan of a.py only: b.py's entries are out of scope, not stale
    partial = run_lint(
        str(tmp_path), paths=["pkg/a.py"], baseline=baseline, select={"CDT001"}
    )
    assert partial.stale_baseline == []
    # checker-scoped scan: CDT001 entries out of scope for a CDT004-only run
    other = run_lint(
        str(tmp_path), paths=["pkg/a.py", "pkg/b.py"], baseline=baseline,
        select={"CDT004"},
    )
    assert other.stale_baseline == []
    # full-scope scan with everything intact: nothing stale either
    intact = run_lint(
        str(tmp_path), paths=["pkg/b.py"], baseline=baseline, select={"CDT001"}
    )
    assert intact.stale_baseline == [] and intact.findings == []


def test_update_baseline_converges_with_duplicate_offending_lines(tmp_path):
    """A new finding on a line textually identical to an already
    baselined one must fingerprint at the next occurrence index, so
    baseline + rescan converges to green instead of colliding."""
    from tools.cdtlint.runner import compute_fingerprints

    target = tmp_path / "pkg" / "mod.py"
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text("import time\n\nasync def f():\n    time.sleep(1)\n")
    first = run_lint(str(tmp_path), paths=["pkg/mod.py"], select={"CDT001"})
    baseline = Baseline(path=str(tmp_path / "baseline.json"))
    baseline.entries = compute_fingerprints(str(tmp_path), first.findings)
    assert len(baseline.entries) == 1
    # add a second, textually identical offending line
    target.write_text(
        "import time\n\nasync def f():\n    time.sleep(1)\n    time.sleep(1)\n"
    )
    second = run_lint(
        str(tmp_path), paths=["pkg/mod.py"], baseline=baseline, select={"CDT001"}
    )
    assert len(second.baselined) == 1 and len(second.findings) == 1
    new_entries = compute_fingerprints(
        str(tmp_path), second.findings, already_baselined=second.baselined
    )
    assert set(new_entries).isdisjoint(baseline.entries)  # no collision
    baseline.entries.update(new_entries)
    third = run_lint(
        str(tmp_path), paths=["pkg/mod.py"], baseline=baseline, select={"CDT001"}
    )
    assert third.findings == [] and third.stale_baseline == []


def test_config_docs_generator_check_mode():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "gen_config_docs.py"), "--check"],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
