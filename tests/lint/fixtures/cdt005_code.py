"""CDT005 fixture: code reading knobs + declaring metrics.

Mounted into a synthetic project tree by the tests; the companion
registry fixture declares CDT_FIXTURE_DOCUMENTED (documented) and
CDT_FIXTURE_STALE (read by nobody).
"""

import os

DOCUMENTED = os.environ.get("CDT_FIXTURE_DOCUMENTED", "1")
MISSING = os.environ.get("CDT_FIXTURE_UNDECLARED")  # finding: not in registry


def declare_metrics(registry):
    ok_counter = registry.counter("cdt_fixture_events_total", "fine")
    ok_gauge = registry.gauge("cdt_fixture_depth", "fine")
    bad_prefix = registry.counter("fixture_events_total", "finding: prefix")
    bad_counter = registry.counter("cdt_fixture_events", "finding: no _total")
    bad_gauge = registry.gauge("cdt_fixture_depth_total", "finding: gauge _total")
    return ok_counter, ok_gauge, bad_prefix, bad_counter, bad_gauge
