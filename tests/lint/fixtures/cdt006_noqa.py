"""CDT006 fixture: suppressed inline declaration (migration window)."""


def transitional(registry):
    return registry.gauge("cdt_fixture_transitional", "moving soon")  # cdt: noqa[CDT006]
