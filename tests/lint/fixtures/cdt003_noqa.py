"""CDT003 suppressed: deliberate trace-time constant bake."""

import jax
import numpy as np


@jax.jit
def bakes_a_table(x):
    # the table is module-constant by design; baking it is the point
    table = np.asarray([1.0, 2.0, 4.0])  # cdt: noqa[CDT003]
    return x * table[0]
