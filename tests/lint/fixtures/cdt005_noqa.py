"""CDT005 fixture: suppressed undeclared knob read (migration window)."""

import os

TRANSITIONAL = os.environ.get("CDT_FIXTURE_TRANSITIONAL")  # cdt: noqa[CDT005]
