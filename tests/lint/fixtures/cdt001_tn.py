"""CDT001 true negatives: the sanctioned async patterns."""

import asyncio
import threading
import time
from asyncio import sleep

_lock = threading.Lock()


async def sleeps_async():
    await asyncio.sleep(1.0)  # asyncio.sleep is fine
    await sleep(0.1)  # `from asyncio import sleep` resolves harmless


async def executor_wrapped_lock():
    loop = asyncio.get_running_loop()
    # passing the bound method UNCALLED is the sanctioned pattern
    await loop.run_in_executor(None, _lock.acquire)
    try:
        pass
    finally:
        _lock.release()


async def executor_wrapped_io(path):
    def _read() -> bytes:
        # nested sync def runs off-loop: open/time.sleep here are fine
        time.sleep(0.0)
        with open(path, "rb") as fh:
            return fh.read()

    return await asyncio.get_running_loop().run_in_executor(None, _read)


def sync_caller_may_block():
    time.sleep(0.0)  # not async: out of scope for CDT001
