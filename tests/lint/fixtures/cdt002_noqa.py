"""CDT002 suppressed: justified single-line suppression."""

import threading

_tlock = threading.Lock()


async def audited_hold(fetch):
    # audited: awaited call is a loop-local future that cannot contend
    with _tlock:  # cdt: noqa[CDT002]
        return await fetch()
