"""CDT002 true positives: lock discipline violations."""

import asyncio
import threading


class Store:
    def __init__(self):
        self._tlock = threading.Lock()
        self._alock = asyncio.Lock()

    async def held_across_await(self, session):
        with self._tlock:  # finding: threading lock held across await
            data = await session.get("/state")
            return data

    def sync_with_on_asyncio_lock(self):
        with self._alock:  # finding: sync `with` on asyncio lock
            return 1

    def sync_acquire_on_asyncio_lock(self):
        self._alock.acquire()  # finding: un-awaited coroutine
        return 2


_module_tlock = threading.Lock()


async def module_lock_across_await(fetch):
    with _module_tlock:  # finding: threading lock held across await
        return await fetch()
