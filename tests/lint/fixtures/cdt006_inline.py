"""CDT006 fixture: a literal cdt_* instrument declared OUTSIDE the
instrument registry (finding: breaks the one-registry idiom)."""


def rogue(registry):
    return registry.gauge("cdt_fixture_inline", "finding: inline declaration")
