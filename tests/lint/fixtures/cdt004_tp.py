"""CDT004 true positives: ordering/entropy hazards.

Tests mount this at a DETERMINISM_PATHS location before linting.
"""

import glob
import os
import random


def blend_in_arrival_order(done_tiles, canvas, results):
    for idx in done_tiles | {0}:  # finding: set iteration unsorted
        canvas += results[idx]
    return canvas


def iterate_set_literal():
    return [x for x in {3, 1, 2}]  # finding: set literal in comprehension


def list_dir_unsorted(path):
    out = []
    for name in os.listdir(path):  # finding: readdir order
        out.append(name)
    out.extend(glob.glob("*.png"))  # finding: glob order
    return out


def ambient_entropy(grid):
    jitter = random.random()  # finding: global RNG
    return jitter * len(grid)


def clock_seed(fold_in, key):
    import time

    return fold_in(key, time.time())  # finding: wall clock as seed material
