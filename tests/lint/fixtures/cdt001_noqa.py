"""CDT001 suppressed: inline noqa acknowledges a deliberate block."""

import time


async def deliberate_blocking_probe():
    # sub-millisecond by construction; measured, documented, accepted
    time.sleep(0.0005)  # cdt: noqa[CDT001]


async def blanket_suppressed():
    time.sleep(0.0005)  # cdt: noqa
