"""CDT001 true positives: blocking calls lexically inside async defs."""

import subprocess
import threading
import time

import requests

_lock = threading.Lock()


async def sleeps_on_loop():
    time.sleep(1.0)  # finding: time.sleep


async def sync_http():
    return requests.get("http://example.com")  # finding: requests.get


async def shells_out():
    subprocess.run(["true"])  # finding: subprocess.run


async def grabs_lock():
    _lock.acquire()  # finding: threading lock acquire
    try:
        pass
    finally:
        _lock.release()


async def reads_file(path):
    with open(path) as fh:  # finding: sync open
        return fh.read()
