"""CDT004 suppressed: order-insensitive aggregation, justified inline.

Tests mount this at a DETERMINISM_PATHS location before linting.
"""


def count_members(done_tiles):
    total = 0
    # membership counting is order-insensitive: iteration order cannot
    # affect the integer result
    for _ in done_tiles | {0}:  # cdt: noqa[CDT004]
        total += 1
    return total
