"""CDT007 true positives: host syncs inside the device-resident hot
path (fixture is mounted at a HOT_PATH_PATHS location by the test)."""
import jax
import numpy as np


def retire(out, ensure_numpy):
    host = np.asarray(out)  # implicit __array__ d2h
    contig = np.ascontiguousarray(out)  # same pull, contiguous
    stacked = np.stack([out, out])  # stack forces __array__ per item
    pulled = jax.device_get(out)  # explicit d2h
    out.block_until_ready()  # method-form host sync barrier
    jax.block_until_ready(out)  # functional-form sync barrier
    mat = ensure_numpy(out)  # the repo's materialization helper
    return host, contig, stacked, pulled, mat
