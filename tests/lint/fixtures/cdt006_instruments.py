"""CDT006 fixture registry (mounted as telemetry/instruments.py):
declares one documented metric and one missing from the doc."""


def fixture_ok_total(registry):
    return registry.counter("cdt_fixture_ok_total", "documented in the doc")


def fixture_undocumented_total(registry):
    return registry.counter(
        "cdt_fixture_undocumented_total", "finding: not in the doc"
    )
