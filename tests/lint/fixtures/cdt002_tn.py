"""CDT002 true negatives: correct lock usage on both sides."""

import asyncio
import threading


class Store:
    def __init__(self):
        self._tlock = threading.Lock()
        self._alock = asyncio.Lock()

    async def asyncio_lock_across_await(self, session):
        async with self._alock:  # asyncio lock may span awaits
            return await session.get("/state")

    async def threading_lock_no_await(self):
        with self._tlock:  # held for a pure-sync critical section: fine
            return dict(x=1)

    def sync_threading_lock(self):
        with self._tlock:
            return 1

    def sync_probe(self):
        return self._alock.locked()  # read-only probe is exempt


async def plain_context_manager(span):
    with span("stage"):  # not a lock: never flagged
        await asyncio.sleep(0)
