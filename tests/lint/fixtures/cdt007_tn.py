"""CDT007 true negatives: device-side math and host-side byte plumbing
that never pulls a device array."""
import jax.numpy as jnp
import numpy as np


def blend(region, tile, mask):
    # device-resident compositing: jnp ops stay on-device
    out = region * (1.0 - mask) + tile * mask
    return jnp.asarray(out, dtype=jnp.float32)


def decode(meta, raw):
    # frombuffer/dtype work on host bytes, not device arrays
    dtype = np.dtype(meta["dtype"])
    return np.frombuffer(raw, dtype=dtype)
