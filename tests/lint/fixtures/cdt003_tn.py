"""CDT003 true negatives: sanctioned trace-time patterns."""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("sigmas_t", "cfg"))
def static_args_are_concrete(x, sigmas_t, cfg):
    # concretizing STATIC parameters is the hoist-a-constant idiom
    last = float(sigmas_t[-1])
    return x * last * float(cfg)


def make_processor(cfg):
    @jax.jit
    def process(x, key):
        # closure constants are concrete at trace time
        scale = float(cfg)
        noise = jax.random.normal(key, x.shape)
        return jnp.tanh(x * scale) + noise

    return process


@jax.jit
def debug_print_is_fine(x):
    jax.debug.print("x={x}", x=x)
    return jnp.sum(x)


def untraced_host_code(x):
    # not traced: host sync, wall clock, numpy all fine here
    arr = np.asarray(x)
    _ = time.time()
    return float(arr.sum())
