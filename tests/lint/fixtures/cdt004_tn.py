"""CDT004 true negatives: sorted iteration and explicit keys."""

import glob
import os


def blend_sorted(done_tiles, canvas, results):
    for idx in sorted(done_tiles | {0}):  # sorted set: deterministic
        canvas += results[idx]
    return canvas


def enumerate_sorted_listing(path):
    return [
        (i, name)
        for i, name in enumerate(sorted(os.listdir(path)))  # sorted listing
    ] + sorted(glob.glob("*.png"))


def list_iteration(tiles):
    return [t * 2 for t in tiles]  # plain list: ordering well-defined


def explicit_key_entropy(key, fold_in, tile_idx):
    return fold_in(key, tile_idx)  # explicit deterministic key derivation
