"""CDT005 fixture registry (mounted as utils/knob_registry.py)."""

from typing import NamedTuple


class Knob(NamedTuple):
    name: str
    default: str
    subsystem: str
    effect: str


KNOBS = (
    Knob("CDT_FIXTURE_DOCUMENTED", "1", "fixtures", "a documented, read knob"),
    Knob("CDT_FIXTURE_STALE", "0", "fixtures", "declared but read by nobody"),
)
