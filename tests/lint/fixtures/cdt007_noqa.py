"""CDT007 noqa: the sanctioned, ledger-bracketed readback seam."""
import numpy as np


def spill(x):
    # the checkpoint spill's one host copy, ledger-bracketed upstream
    return np.asarray(x)  # cdt: noqa[CDT007]
