"""CDT003 true positives: host-sync / entropy inside traced functions."""

import random
import time
from functools import partial

import jax
import numpy as np


@jax.jit
def decorated_jit(x):
    return np.asarray(x)  # finding: host sync


@partial(jax.jit, static_argnames=("n",))
def concretizes_traced_param(x, n):
    scale = float(x)  # finding: x is a traced (non-static) parameter
    return scale * n


@jax.jit
def syncs_and_prints(x):
    print("tracing", x)  # finding: print runs once at trace time
    y = x.block_until_ready()  # finding: host sync
    return y.item()  # finding: concretizes


@jax.jit
def python_entropy(x):
    jitter = random.random()  # finding: Python RNG freezes at trace time
    stamp = time.time()  # finding: wall clock freezes at trace time
    return x + jitter + stamp


def referenced_by_vmap(x):
    return x.tolist()  # finding: traced via jax.vmap(referenced_by_vmap)


batched = jax.vmap(referenced_by_vmap)
