"""Acceptance path: a chaos-harness run under the fake clock yields a
trace JSONL from which scripts/perf_report.py reconstructs the
complete tile lifecycle deterministically."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

pytestmark = pytest.mark.chaos

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO_ROOT, "scripts")
sys.path.insert(0, SCRIPTS)

import perf_report  # noqa: E402


@pytest.fixture(scope="module")
def chaos_trace(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("trace") / "chaos.jsonl")
    result = run_chaos_usdu(seed=11, trace_jsonl=path)
    return result, path


def test_chaos_run_exports_trace_jsonl(chaos_trace):
    result, path = chaos_trace
    assert result.trace_id == "exec_chaos_11"
    spans = perf_report.load_spans(path)
    assert spans, "trace export is empty"
    assert all(s["trace_id"] == "exec_chaos_11" for s in spans)
    # fake clock: every span finished with a deterministic duration
    assert all(s["end"] is not None for s in spans)
    names = {s["name"] for s in spans}
    assert "chaos_usdu" in names
    assert {"tile.pull", "tile.sample", "tile.blend"} <= names


def test_report_reconstructs_complete_tile_lifecycle(chaos_trace):
    result, path = chaos_trace
    spans = perf_report.load_spans(path)
    tiles = perf_report.tile_lifecycle(spans)
    # the 64→128 upscale at tile=64/padding=16 yields a 2x2 grid
    assert sorted(tiles) == [0, 1, 2, 3]
    problems = perf_report.incomplete_tiles(tiles)
    assert problems == {}, problems
    report = perf_report.build_report(spans)
    assert report["unfinished_spans"] == 0
    for stage in ("tile.pull", "tile.sample", "tile.blend"):
        assert report["stages"][stage]["count"] >= 1, stage
    # every tile was blended exactly once
    assert report["stages"]["tile.blend"]["count"] == 4


def test_lifecycle_reconstruction_is_deterministic(tmp_path):
    """Thread scheduling may change WHO processes a tile, but the
    reconstructed lifecycle is complete every run and the blended
    output is bit-identical — the property perf analysis relies on."""
    outputs = []
    for run in range(2):
        path = str(tmp_path / f"t{run}.jsonl")
        result = run_chaos_usdu(seed=11, trace_jsonl=path)
        outputs.append(result.output)
        tiles = perf_report.tile_lifecycle(perf_report.load_spans(path))
        assert sorted(tiles) == [0, 1, 2, 3]
        assert perf_report.incomplete_tiles(tiles) == {}
    np.testing.assert_array_equal(outputs[0], outputs[1])


def test_lifecycle_complete_under_worker_crash(tmp_path):
    """A crash-after-pull still yields a complete reconstructed
    lifecycle: the requeued tile's successful attempt closes it."""
    path = str(tmp_path / "crash.jsonl")
    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            "seed=11;latency(0.15)@store:pull:master#1-3;"
            "crash@chaos:w1:pulled#1"
        ),
        trace_jsonl=path,
    )
    assert "w1" in result.crashed_workers
    spans = perf_report.load_spans(path)
    tiles = perf_report.tile_lifecycle(spans)
    assert perf_report.incomplete_tiles(tiles) == {}
    # the crashed attempt left an unfinished or error span behind —
    # visible in the report, not silently dropped
    w1_spans = [
        s for s in spans if (s.get("attrs") or {}).get("worker_id") == "w1"
    ]
    assert w1_spans


def test_cli_renders_report(chaos_trace):
    _result, path = chaos_trace
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "perf_report.py"), path],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "tile lifecycles: 4 tile(s)" in proc.stdout
    assert "all tile lifecycles complete" in proc.stdout
    assert "tile.sample" in proc.stdout

    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"), path,
            "--json", "--trace", "exec_chaos_11",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    data = json.loads(proc.stdout)
    assert data["incomplete"] == {}
    assert set(data["tiles"]) == {"0", "1", "2", "3"}


def _span(name, duration, idx=0):
    return {
        "trace_id": "t", "span_id": f"s{name}{idx}{duration}", "parent_id": None,
        "name": name, "start": 0.0, "end": duration, "duration": duration,
        "attrs": {}, "events": [], "status": "ok",
    }


def _write_jsonl(path, spans):
    with open(path, "w", encoding="utf-8") as fh:
        for span in spans:
            fh.write(json.dumps(span) + "\n")


def test_report_includes_p99_column(chaos_trace):
    _result, path = chaos_trace
    report = perf_report.build_report(perf_report.load_spans(path))
    for stats in report["stages"].values():
        assert "p99" in stats
        assert stats["p50"] <= stats["p95"] <= stats["p99"] <= stats["max"]
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "perf_report.py"), path],
        capture_output=True, text=True, timeout=120,
    )
    assert "p99_s" in proc.stdout


def test_compare_flags_p95_regressions_only():
    old = perf_report.build_report(
        [_span("tile.sample", 0.1, i) for i in range(10)]
        + [_span("tile.pull", 0.01, i) for i in range(10)]
    )
    new = perf_report.build_report(
        [_span("tile.sample", 0.2, i) for i in range(10)]   # +100%
        + [_span("tile.pull", 0.011, i) for i in range(10)]  # +10%
        + [_span("tile.freshly_added", 9.0)]                 # no baseline
    )
    regressions = perf_report.compare_reports(old, new, regress_pct=25.0)
    assert [r["stage"] for r in regressions] == ["tile.sample"]
    assert regressions[0]["delta_pct"] == pytest.approx(100.0)
    # a looser gate passes everything
    assert perf_report.compare_reports(old, new, regress_pct=150.0) == []


def test_cli_compare_exits_nonzero_on_regression(tmp_path):
    old_path = str(tmp_path / "old.jsonl")
    new_path = str(tmp_path / "new.jsonl")
    _write_jsonl(old_path, [_span("tile.sample", 0.1, i) for i in range(5)])
    _write_jsonl(new_path, [_span("tile.sample", 0.5, i) for i in range(5)])
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            new_path, "--compare", old_path, "--regress-pct", "25",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "REGRESSIONS" in proc.stdout
    assert "tile.sample" in proc.stdout

    # same trace compared against itself: clean exit
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            new_path, "--compare", new_path,
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no stage regressed" in proc.stdout

    # --json carries the regression list for machine consumers
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            new_path, "--compare", old_path, "--json",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 3
    data = json.loads(proc.stdout)
    assert data["regressions"][0]["stage"] == "tile.sample"


def _sched_span(trace_id, start, duration, idx=0):
    return {
        "trace_id": trace_id, "span_id": f"sw{trace_id}{idx}", "parent_id": None,
        "name": "sched.wait", "start": start, "end": start + duration,
        "duration": duration, "attrs": {"lane": "interactive"}, "events": [],
        "status": "ok",
    }


def _pull_span(trace_id, start, idx=0):
    return {
        "trace_id": trace_id, "span_id": f"pl{trace_id}{idx}", "parent_id": None,
        "name": "tile.pull", "start": start, "end": start + 0.01,
        "duration": 0.01, "attrs": {"stage": "pull", "role": "master"},
        "events": [], "status": "ok",
    }


def test_queue_wait_pairs_admission_with_first_pull():
    spans = [
        _sched_span("t1", start=0.0, duration=0.5),
        _pull_span("t1", start=2.0),      # first pull: wait = 2.0
        _pull_span("t1", start=5.0, idx=1),  # later pulls ignored
        _sched_span("t2", start=1.0, duration=0.25),  # no pull → grant wait
    ]
    stats = perf_report.queue_wait_stats(spans)
    assert stats["count"] == 2
    assert stats["max"] == pytest.approx(2.0)
    assert stats["p50"] in (pytest.approx(0.25), pytest.approx(2.0))
    report = perf_report.build_report(spans)
    assert report["queue_wait"]["count"] == 2
    # pre-scheduler traces: column absent, not zero
    assert perf_report.queue_wait_stats([_pull_span("t", 0.0)]) is None


def test_queue_wait_rides_the_compare_gate(tmp_path):
    old = perf_report.build_report(
        [_sched_span("t", 0.0, 0.1), _pull_span("t", 0.1)]
        + [_span("tile.sample", 0.1, i) for i in range(5)]
    )
    new = perf_report.build_report(
        [_sched_span("t", 0.0, 0.1), _pull_span("t", 1.0)]  # 10x wait
        + [_span("tile.sample", 0.1, i) for i in range(5)]
    )
    regressions = perf_report.compare_reports(old, new, regress_pct=25.0)
    assert [r["stage"] for r in regressions] == ["queue_wait"]
    assert regressions[0]["delta_pct"] > 100

    # CLI exit code 3 through the same path
    old_path, new_path = str(tmp_path / "o.jsonl"), str(tmp_path / "n.jsonl")
    _write_jsonl(old_path, [_sched_span("t", 0.0, 0.1), _pull_span("t", 0.1)])
    _write_jsonl(new_path, [_sched_span("t", 0.0, 0.1), _pull_span("t", 1.0)])
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            new_path, "--compare", old_path,
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "queue_wait" in proc.stdout


def test_queue_wait_rendered_in_text_report(tmp_path):
    path = str(tmp_path / "w.jsonl")
    _write_jsonl(path, [_sched_span("t", 0.0, 0.5), _pull_span("t", 0.5)])
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "perf_report.py"), path],
        capture_output=True, text=True, timeout=120,
    )
    assert "queue wait (admission -> first pull)" in proc.stdout
    assert "p95=" in proc.stdout


def _stage_span(stage, start, duration, idx=0, role="worker"):
    return {
        "trace_id": "t", "span_id": f"st{stage}{idx}", "parent_id": None,
        "name": f"tile.{stage}", "start": start, "end": start + duration,
        "duration": duration, "attrs": {"stage": stage, "role": role},
        "events": [], "status": "ok",
    }


def test_pipeline_overlap_measures_sample_io_concurrency():
    # sample [0,1] while submit rides [0.5, 1.5] → 0.5s of the 1.0s
    # sample wall overlapped; a second sample [2,3] with no concurrent
    # I/O adds wall but no overlap.
    spans = [
        _stage_span("sample", 0.0, 1.0),
        _stage_span("submit", 0.5, 1.0),
        _stage_span("sample", 2.0, 1.0, idx=1),
    ]
    stats = perf_report.pipeline_overlap_stats(spans)
    assert stats["sample_wall"] == pytest.approx(2.0)
    assert stats["overlapped"] == pytest.approx(0.5)
    assert stats["fraction"] == pytest.approx(0.25)
    # fully serial: encode/submit strictly between samples → 0.0
    serial = perf_report.pipeline_overlap_stats(
        [
            _stage_span("sample", 0.0, 1.0),
            _stage_span("encode", 1.0, 0.5),
            _stage_span("sample", 1.5, 1.0, idx=1),
        ]
    )
    assert serial["fraction"] == pytest.approx(0.0)
    # no I/O spans at all → column absent, not zero
    assert perf_report.pipeline_overlap_stats(
        [_stage_span("sample", 0.0, 1.0)]
    ) is None


def test_pipeline_overlap_ignores_cross_worker_concurrency():
    """Two fully serial per-worker pipelines whose stages interleave in
    wall time: fleet parallelism must NOT read as pipeline overlap —
    spans intersect per (role, worker_id) only."""
    def w(stage, start, duration, wid, idx=0):
        span = _stage_span(stage, start, duration, idx=f"{wid}{idx}")
        span["attrs"]["worker_id"] = wid
        return span

    spans = [
        # w1: sample [0,1], submit [1,2] (serial); w2 shifted by 0.5 so
        # w2's sample overlaps w1's submit in wall time
        w("sample", 0.0, 1.0, "w1"),
        w("submit", 1.0, 1.0, "w1"),
        w("sample", 0.5, 1.0, "w2"),
        w("submit", 1.5, 1.0, "w2"),
    ]
    stats = perf_report.pipeline_overlap_stats(spans)
    assert stats["fraction"] == pytest.approx(0.0)
    # the same timeline attributed to ONE worker IS overlap
    merged = [
        w("sample", 0.0, 1.0, "w1"),
        w("submit", 1.0, 1.0, "w1"),
        w("sample", 0.5, 1.0, "w1", idx=1),
        w("submit", 1.5, 1.0, "w1", idx=1),
    ]
    # sample2 [0.5,1.5] ∩ io-union [1,2.5] = 0.5 of 2.0 sample wall
    assert perf_report.pipeline_overlap_stats(merged)["fraction"] == pytest.approx(0.25)


def test_pipeline_overlap_rides_the_compare_gate(tmp_path):
    overlapped = [
        _stage_span("sample", 0.0, 1.0),
        _stage_span("submit", 0.0, 1.0),
    ]
    serial = [
        _stage_span("sample", 0.0, 1.0),
        _stage_span("submit", 1.0, 1.0),
        # keep sample p95 identical so only the overlap gate can fire
    ]
    old = perf_report.build_report(overlapped)
    new = perf_report.build_report(serial)
    regressions = perf_report.compare_reports(old, new, regress_pct=25.0)
    assert [r["stage"] for r in regressions] == ["pipeline_overlap"]
    assert regressions[0]["delta_pct"] == pytest.approx(100.0)
    # overlap improving (or staying) is never a regression
    assert perf_report.compare_reports(new, old, regress_pct=25.0) == []

    old_path, new_path = str(tmp_path / "o.jsonl"), str(tmp_path / "n.jsonl")
    _write_jsonl(old_path, overlapped)
    _write_jsonl(new_path, serial)
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            new_path, "--compare", old_path,
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "pipeline_overlap" in proc.stdout


def test_pipeline_overlap_rendered_in_text_report(tmp_path):
    path = str(tmp_path / "ov.jsonl")
    _write_jsonl(
        path,
        [_stage_span("sample", 0.0, 1.0), _stage_span("submit", 0.5, 1.0)],
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "perf_report.py"), path],
        capture_output=True, text=True, timeout=120,
    )
    assert "pipeline overlap" in proc.stdout
    assert "fraction" in proc.stdout


def test_batched_sample_spans_credit_every_tile_in_lifecycle():
    span = _stage_span("sample", 0.0, 1.0)
    span["attrs"]["batch"] = [4, 5, 6]
    span["attrs"]["tile_idx"] = 4
    tiles = perf_report.tile_lifecycle([span])
    assert sorted(tiles) == [4, 5, 6]
    for stages in tiles.values():
        assert stages[0]["stage"] == "sample"


def _write_spans(path, spans):
    with open(path, "w") as fh:
        for span in spans:
            fh.write(json.dumps(span) + "\n")


def _raw_span(name, start, end, trace="t1", **attrs):
    return {
        "trace_id": trace, "span_id": f"{name}-{start}", "parent_id": None,
        "name": name, "start": start, "end": end, "duration": end - start,
        "attrs": attrs, "events": [], "status": "ok",
    }


def test_slo_gate_flags_p95_over_budget_and_missing_stage(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    _write_spans(path, [
        _raw_span("tile.sample", 0.0, 0.5),
        _raw_span("tile.sample", 1.0, 1.2),
    ])
    report = perf_report.build_report(perf_report.load_spans(path))
    violations = perf_report.slo_violations(
        report, {"tile.sample": 0.3, "tile.encode": 1.0}
    )
    assert {v["stage"]: v["missing"] for v in violations} == {
        "tile.sample": False, "tile.encode": True,
    }
    assert not perf_report.slo_violations(report, {"tile.sample": 1.0})


def test_cli_slo_exit_code_and_rendering(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    _write_spans(path, [_raw_span("tile.sample", 0.0, 0.5)])
    base = [sys.executable, os.path.join(SCRIPTS, "perf_report.py"), path]
    ok = subprocess.run(
        base + ["--slo", "tile.sample=2.0"],
        capture_output=True, text=True, timeout=60,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "every budgeted stage p95 within target" in ok.stdout
    bad = subprocess.run(
        base + ["--slo", "tile.sample=0.1", "--json"],
        capture_output=True, text=True, timeout=60,
    )
    assert bad.returncode == 4
    payload = json.loads(bad.stdout)
    assert payload["slo_violations"][0]["stage"] == "tile.sample"
    malformed = subprocess.run(
        base + ["--slo", "tile.sample"],
        capture_output=True, text=True, timeout=60,
    )
    assert malformed.returncode == 1


def test_cli_fails_on_missing_or_empty_input(tmp_path):
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            str(tmp_path / "empty.jsonl"),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode != 0


# --------------------------------------------------------------------------
# --usage: chip-second attribution from dispatch spans
# --------------------------------------------------------------------------


def _dispatch_span(duration, real, bucket, jobs=None, tenants=None,
                   recompute=0, start=0.0, adapter=False):
    return {
        "trace_id": "t", "span_id": f"d{start}", "name": "tile.dispatch",
        "start": start, "duration": duration,
        "attrs": {
            "stage": "dispatch", "role": "worker", "real": real,
            "bucket": bucket, "jobs": len(jobs or {"j": real}),
            "slot_jobs": jobs or {"j": real},
            "slot_tenants": tenants or {},
            "recompute": recompute,
            "adapter": adapter,
        },
    }


def test_adapter_stats_scope_and_share():
    spans = [
        _dispatch_span(1.0, 4, 4),                              # base batch
        _dispatch_span(1.0, 3, 4, start=1.0, adapter=True),     # worn
        _dispatch_span(1.0, 2, 4, start=2.0, adapter=True),     # worn
    ]
    stats = perf_report.adapter_stats(spans)
    assert stats["dispatches"] == 3
    assert stats["adapter_dispatches"] == 2
    assert stats["dispatch_share"] == pytest.approx(2 / 3)
    assert stats["adapter_fill"] == pytest.approx(5 / 8)
    # an adapter-less trace stays comparable: absence is None, not 0
    assert perf_report.adapter_stats([_dispatch_span(1.0, 4, 4)]) is None


def test_adapter_fill_drop_rides_the_compare_gate():
    old = perf_report.build_report([_dispatch_span(1.0, 4, 4, adapter=True)])
    new = perf_report.build_report([_dispatch_span(1.0, 1, 4, adapter=True)])
    regressions = perf_report.compare_reports(old, new, regress_pct=25.0)
    assert any(r["stage"] == "adapter_fill" for r in regressions)
    rendered = perf_report.render_comparison(regressions, 25.0)
    assert "adapter_fill" in rendered
    # unchanged fill passes; missing on either side is not a regression
    assert not any(
        r["stage"] == "adapter_fill"
        for r in perf_report.compare_reports(new, new, regress_pct=25.0)
    )
    base = perf_report.build_report([_dispatch_span(1.0, 4, 4)])
    assert not any(
        r["stage"] == "adapter_fill"
        for r in perf_report.compare_reports(base, new, regress_pct=25.0)
    )


def test_usage_stats_splits_span_wall_across_slots():
    spans = [
        # 1.0s over 4 slots: 3 real (2 t-a, 1 t-b) + 1 padding
        _dispatch_span(1.0, 3, 4, jobs={"ja": 2, "jb": 1},
                       tenants={"t-a": 2, "t-b": 1}),
        # 0.5s fully real, one recompute slot counted as waste
        _dispatch_span(0.5, 2, 2, jobs={"ja": 2}, tenants={"t-a": 2},
                       recompute=1, start=2.0),
    ]
    usage = perf_report.usage_stats(spans)
    assert usage["dispatches"] == 2
    assert usage["total_s"] == pytest.approx(1.5)
    # waste: 1 padding slot x 0.25 + 1 recompute slot x 0.25
    assert usage["waste_s"] == pytest.approx(0.5)
    assert usage["waste_share"] == pytest.approx(0.5 / 1.5)
    assert usage["tenants"]["t-a"]["chip_s"] == pytest.approx(
        2 * 0.25 + 2 * 0.25
    )
    assert usage["tenants"]["t-b"]["chip_s"] == pytest.approx(0.25)
    assert usage["jobs"]["jb"]["share"] == pytest.approx(0.25 / 1.5)
    # no dispatch spans -> None (a scan trace predating the column)
    assert perf_report.usage_stats([{"name": "tile.sample"}]) is None


def test_usage_waste_share_growth_rides_the_compare_gate(tmp_path):
    old = [_dispatch_span(1.0, 4, 4)]  # no waste
    new = [_dispatch_span(1.0, 2, 4)]  # 50% padding
    regressions = perf_report.usage_regressions(
        perf_report.usage_stats(old), perf_report.usage_stats(new), 25.0
    )
    assert regressions and regressions[0]["stage"] == "usage_waste_share"
    assert regressions[0]["new_share"] == pytest.approx(0.5)
    # unchanged waste passes
    assert not perf_report.usage_regressions(
        perf_report.usage_stats(new), perf_report.usage_stats(new), 25.0
    )
    rendered = perf_report.render_comparison(regressions, 25.0)
    assert "usage_waste_share" in rendered and "share" in rendered
    # CLI round trip: exit 3 on the waste growth, 0 against itself
    old_path, new_path = tmp_path / "old.jsonl", tmp_path / "new.jsonl"
    old_path.write_text("\n".join(json.dumps(s) for s in old))
    new_path.write_text("\n".join(json.dumps(s) for s in new))
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            str(new_path), "--usage", "--compare", str(old_path),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 3, proc.stdout + proc.stderr
    assert "usage_waste_share" in proc.stdout
    proc = subprocess.run(
        [
            sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
            str(new_path), "--usage", "--compare", str(new_path), "--json",
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode in (0, 2), proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["usage"]["waste_share"] == pytest.approx(0.5)


def test_scan_tier_chaos_trace_carries_dispatch_spans(tmp_path):
    """Both tiers emit tile.dispatch now: a scan-tier chaos trace must
    feed the --usage column (job attribution via slot_jobs)."""
    trace = tmp_path / "scan.jsonl"
    run_chaos_usdu(seed=5, tile_batch=2, trace_jsonl=str(trace))
    spans = perf_report.load_spans(str(trace))
    usage = perf_report.usage_stats(spans)
    assert usage is not None and usage["dispatches"] > 0
    assert "chaos-job" in usage["jobs"]


def test_usage_waste_gate_tolerates_near_zero_noise():
    """0.99% -> 1.01% is jitter, not a regression; 0% -> 3% fails on
    absolute growth past one point."""
    base = perf_report.usage_stats([_dispatch_span(1.0, 4, 4)])
    noisy_old = dict(base, waste_share=0.0099)
    noisy_new = dict(base, waste_share=0.0101)
    assert not perf_report.usage_regressions(noisy_old, noisy_new, 25.0)
    grown = dict(base, waste_share=0.03)
    hits = perf_report.usage_regressions(noisy_old, grown, 25.0)
    assert hits and hits[0]["stage"] == "usage_waste_share"


# --------------------------------------------------------------------------
# tile-cache serving reconstruction (content-addressed cache PR)
# --------------------------------------------------------------------------


def _cache_span(stage, idx=0, attrs=None):
    span_attrs = {"stage": stage, "role": "master"}
    if stage == "cache.hit":
        span_attrs["tile_idx"] = idx
    span_attrs.update(attrs or {})
    return {
        "trace_id": "t", "span_id": f"c{stage}{idx}", "parent_id": None,
        "name": f"tile.{stage}", "start": 0.0, "end": 0.001,
        "duration": 0.001, "attrs": span_attrs, "events": [], "status": "ok",
    }


def test_warm_cache_trace_hit_rate_and_complete_lifecycles(tmp_path):
    """A warm (fully cache-served) chaos trace: the report's cache
    column reads 100% hits with zero dispatched tiles, and every tile's
    lifecycle is complete even though NOBODY sampled or blended it —
    the master's tile.cache.hit span closes it."""
    from comfyui_distributed_tpu.cache.store import TileResultCache

    cache = TileResultCache(ram_mb=64)
    run_chaos_usdu(seed=11, cache=cache)  # cold populate
    path = str(tmp_path / "warm.jsonl")
    result = run_chaos_usdu(seed=11, cache=cache, trace_jsonl=path)
    assert result.cache["settled"] == 4
    spans = perf_report.load_spans(path)
    report = perf_report.build_report(spans)
    assert report["cache"] == {
        "probes": 1, "hits": 4, "dispatched_tiles": 0, "hit_rate": 1.0,
    }
    tiles = perf_report.tile_lifecycle(spans)
    assert sorted(tiles) == [0, 1, 2, 3]
    assert perf_report.incomplete_tiles(tiles) == {}
    # the text report surfaces the serving rate
    rendered = perf_report.render_text(
        report, tiles, perf_report.incomplete_tiles(tiles)
    )
    assert "hit rate 1.000" in rendered


def test_cache_off_trace_reports_no_cache_column(chaos_trace):
    """Absence is not a 0% hit rate: a cache-off trace must have no
    cache block at all (old traces stay comparable)."""
    _result, path = chaos_trace
    report = perf_report.build_report(perf_report.load_spans(path))
    assert report["cache"] is None


def test_cache_hit_rate_drop_rides_the_compare_gate():
    """The inverted gate: tiles the old trace settled near-free going
    back to burning device slots fails --compare."""
    old = perf_report.build_report(
        [_cache_span("cache.probe", attrs={"hits": 4})]
        + [_cache_span("cache.hit", i) for i in range(4)]
    )
    new = perf_report.build_report(
        [_cache_span("cache.probe", attrs={"hits": 1}),
         _cache_span("cache.hit", 0),
         _dispatch_span(1.0, 3, 4)]
    )
    assert old["cache"]["hit_rate"] == 1.0
    assert new["cache"]["hit_rate"] == 0.25
    regressions = perf_report.compare_reports(old, new, 25.0)
    hits = [r for r in regressions if r["stage"] == "cache_hit_rate"]
    assert hits and hits[0]["delta_pct"] == pytest.approx(75.0)
    rendered = perf_report.render_comparison(regressions, 25.0)
    assert "cache_hit_rate" in rendered
    # no gate when the old trace had no cache activity (new
    # instrumentation is not a regression), nor when rates held
    no_cache_old = perf_report.build_report([_dispatch_span(1.0, 4, 4)])
    assert not [
        r
        for r in perf_report.compare_reports(no_cache_old, new, 25.0)
        if r["stage"] == "cache_hit_rate"
    ]
    assert not [
        r
        for r in perf_report.compare_reports(old, old, 25.0)
        if r["stage"] == "cache_hit_rate"
    ]


# --------------------------------------------------------------------------
# host tax + per-tile waterfall (device-time attribution PR)
# --------------------------------------------------------------------------


def _tile_span(stage, tile_idx, start, duration, batch=None, device=None,
                role="worker"):
    attrs = {"stage": stage, "role": role, "tile_idx": tile_idx}
    if batch is not None:
        attrs["batch"] = list(batch)
    if device is not None:
        attrs["device"] = device
    return {
        "trace_id": "t", "span_id": f"s{stage}{tile_idx}{start}",
        "name": f"tile.{stage}", "start": start, "end": start + duration,
        "duration": duration, "attrs": attrs, "events": [], "status": "ok",
    }


def test_host_tax_zero_device_reads_one_not_nan():
    """An eager-stub trace has no device time; the tax must be exactly
    1.0 — all attributable time was host time — never NaN/ZeroDivision."""
    spans = [
        _tile_span("dispatch", 0, 0.0, 0.5, device=False),
        _tile_span("readback", 0, 0.5, 0.1),
        _tile_span("encode", 0, 0.6, 0.1),
    ]
    ht = perf_report.host_tax_stats(spans)
    assert ht["device_ns"] == 0
    assert ht["eager_ns"] == perf_report._to_ns(0.5)
    assert ht["host_tax"] == 1.0


def test_host_tax_device_eager_split():
    spans = [
        _tile_span("dispatch", 0, 0.0, 3.0, device=True),
        _tile_span("dispatch", 1, 3.0, 0.5, device=False),
        _tile_span("readback", 0, 3.5, 0.25),
        _tile_span("submit", 0, 3.75, 0.25),
    ]
    ht = perf_report.host_tax_stats(spans)
    assert ht["dispatches"] == 2
    assert ht["device_dispatches"] == 1
    # host side = eager 0.5 + stages 0.5 = 1.0s against 3.0s device
    assert ht["host_tax"] == pytest.approx(0.25)


def test_host_tax_none_without_signal():
    assert perf_report.host_tax_stats([]) is None
    assert perf_report.host_tax_stats(
        [_tile_span("pull", 0, 0.0, 1.0)]
    ) is None


def test_host_tax_regression_rides_the_compare_gate():
    old = perf_report.build_report([
        _tile_span("dispatch", 0, 0.0, 1.0, device=True),
        _tile_span("readback", 0, 1.0, 0.1),
    ])
    new = perf_report.build_report([
        _tile_span("dispatch", 0, 0.0, 1.0, device=True),
        _tile_span("readback", 0, 1.0, 0.5),
    ])
    assert old["host_tax"]["host_tax"] < new["host_tax"]["host_tax"]
    regressions = perf_report.compare_reports(old, new, 25.0)
    hits = [r for r in regressions if r["stage"] == "host_tax"]
    assert len(hits) == 1
    assert hits[0]["new_share"] == pytest.approx(1.0 / 3.0)
    rendered = perf_report.render_comparison(regressions, 25.0)
    assert "host_tax" in rendered
    # identical traces pass; absence of old signal is not a regression
    assert not [
        r for r in perf_report.compare_reports(old, old, 25.0)
        if r["stage"] == "host_tax"
    ]
    no_signal = perf_report.build_report([_tile_span("pull", 0, 0.0, 1.0)])
    assert not [
        r for r in perf_report.compare_reports(no_signal, new, 25.0)
        if r["stage"] == "host_tax"
    ]


def test_host_tax_near_zero_base_gates_on_absolute_points():
    """0.1% -> 0.9% is noise (sub-point), 0.1% -> 5% is a regression —
    relative growth alone would flag both at +800%/+4900%."""
    base = {"dispatches": 1, "device_dispatches": 1, "device_ns": 10**9,
            "eager_ns": 0, "host_ns": 0, "host_tax": 0.001}
    noisy = dict(base, host_tax=0.009)
    grown = dict(base, host_tax=0.05)
    assert not perf_report.host_tax_regressions(base, noisy, 25.0)
    hits = perf_report.host_tax_regressions(base, grown, 25.0)
    assert hits and hits[0]["delta_pct"] == pytest.approx(4.9)


def test_waterfall_conserves_exactly_with_explicit_waits():
    spans = [
        _tile_span("pull", 0, 0.0, 0.1),
        # 0.1..0.3 gap -> wait
        _tile_span("sample", 0, 0.3, 0.5),
        _tile_span("blend", 0, 0.8, 0.2),
    ]
    wf = perf_report.waterfall_report(spans)
    assert wf["all_conserved"] is True
    tile = wf["tiles"][0]
    assert tile["wall_ns"] == perf_report._to_ns(1.0)
    assert tile["wait_ns"] == perf_report._to_ns(0.2)
    assert sum(tile["stages"].values()) + tile["wait_ns"] == tile["wall_ns"]
    assert [seg["stage"] for seg in tile["timeline"]] == [
        "pull", "wait", "sample", "blend",
    ]


def test_waterfall_batched_spans_credit_every_tile():
    """A batched sample span (batch=[0,1,2]) is every member tile's
    sample segment — tiles 1 and 2 must not read as all-wait."""
    spans = [
        _tile_span("sample", 0, 0.0, 1.0, batch=[0, 1, 2]),
        _tile_span("readback", 0, 1.0, 0.2, batch=[0, 1, 2]),
        _tile_span("encode", 1, 1.2, 0.1),
    ]
    wf = perf_report.waterfall_report(spans)
    assert sorted(wf["tiles"]) == [0, 1, 2]
    assert wf["all_conserved"] is True
    for idx in (0, 1, 2):
        assert wf["tiles"][idx]["stages"]["sample"] == perf_report._to_ns(1.0)
    assert wf["tiles"][1]["stages"]["encode"] == perf_report._to_ns(0.1)
    assert wf["tiles"][2]["wait_ns"] == 0


def test_waterfall_overlap_clipped_not_double_counted():
    """Pipelined d2h/encode overlap: the encode span starts while the
    readback still runs. The overlapped window must be credited ONCE
    (cursor clip), or the stage sum would exceed wall time."""
    spans = [
        _tile_span("readback", 0, 0.0, 0.6),
        _tile_span("encode", 0, 0.4, 0.4),  # 0.4..0.8, overlaps 0.2
        _tile_span("submit", 0, 0.3, 0.2),  # fully inside readback
    ]
    wf = perf_report.waterfall_report(spans)
    tile = wf["tiles"][0]
    assert tile["conserved"] is True
    assert tile["wall_ns"] == perf_report._to_ns(0.8)
    assert tile["stages"]["readback"] == perf_report._to_ns(0.6)
    assert tile["stages"]["encode"] == perf_report._to_ns(0.2)  # clipped
    assert "submit" not in tile["stages"]  # fully shadowed
    assert tile["wait_ns"] == 0


def test_waterfall_chaos_trace_conserves_and_renders(chaos_trace, tmp_path):
    """End-to-end: every tile of a real chaos trace conserves exactly,
    --waterfall --json carries the block, and the CLI exit code is
    clean (5 would mean the attribution broke)."""
    _result, path = chaos_trace
    wf = perf_report.waterfall_report(perf_report.load_spans(path))
    assert sorted(wf["tiles"]) == [0, 1, 2, 3]
    assert wf["all_conserved"] is True
    for tile in wf["tiles"].values():
        assert sum(tile["stages"].values()) + tile["wait_ns"] == tile["wall_ns"]
    rendered = perf_report.render_waterfall(wf)
    assert "conservation OK" in rendered
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "perf_report.py"),
         path, "--waterfall", "--json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stdout[-2000:]
    payload = json.loads(proc.stdout)
    assert payload["waterfall"]["all_conserved"] is True
    assert payload["report"]["host_tax"]["host_tax"] == 1.0  # eager chaos


def test_host_tax_rendered_in_text_report(chaos_trace):
    _result, path = chaos_trace
    spans = perf_report.load_spans(path)
    report = perf_report.build_report(spans)
    tiles = perf_report.tile_lifecycle(spans)
    rendered = perf_report.render_text(
        report, tiles, perf_report.incomplete_tiles(tiles)
    )
    assert "host tax" in rendered
    assert "tax 1.000" in rendered
