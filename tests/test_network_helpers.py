"""URL building / host normalization — parity with reference
tests/test_network_helpers.py scenarios plus cloud-scheme heuristics."""

from comfyui_distributed_tpu.utils import network as net


def test_normalize_host():
    assert net.normalize_host("http://10.0.0.2:8188/") == "10.0.0.2:8188"
    assert net.normalize_host("https://foo.example.com") == "foo.example.com"
    assert net.normalize_host("  localhost ") == "localhost"


def test_split_host_port():
    assert net.split_host_port("10.0.0.2:8188") == ("10.0.0.2", 8188)
    assert net.split_host_port("myhost", 80) == ("myhost", 80)
    assert net.split_host_port("[::1]:9000") == ("::1", 9000)
    assert net.split_host_port("bad:port:xx", 7) == ("bad:port:xx", 7)


def test_worker_url_local_http():
    url = net.build_worker_url({"host": "192.168.1.5", "port": 8189, "type": "local"})
    assert url == "http://192.168.1.5:8189"


def test_worker_url_cloud_https():
    url = net.build_worker_url({"host": "pod.example.io", "port": 443, "type": "cloud"})
    assert url == "https://pod.example.io"


def test_worker_url_runpod_proxy():
    url = net.build_worker_url(
        {"host": "abc-8188.proxy.runpod.net", "port": 0, "type": "remote"}
    )
    assert url.startswith("https://abc-8188.proxy.runpod.net")


def test_worker_url_tunnel():
    url = net.build_worker_url(
        {"host": "rain-bow.trycloudflare.com", "port": 0, "type": "remote"},
        "/distributed/heartbeat",
    )
    assert url == "https://rain-bow.trycloudflare.com/distributed/heartbeat"


def test_master_callback_local_loopback():
    url = net.build_master_callback_url(
        {"type": "local", "host": "whatever.external.ip"}, "1.2.3.4", 8188, "/x"
    )
    assert url == "http://127.0.0.1:8188/x"


def test_master_callback_remote_uses_master_host():
    url = net.build_master_callback_url(
        {"type": "remote", "host": "8.8.8.8"}, "34.1.2.3", 8188, "/x"
    )
    assert url == "http://34.1.2.3:8188/x"


def test_is_private_host():
    assert net.is_private_host("192.168.0.4:8188")
    assert net.is_private_host("localhost")
    assert not net.is_private_host("34.1.2.3")
