"""Offline critical-path analyzer (scripts/incident_report.py): exact
wall-time attribution, bundle/JSONL input handling, and the
perf_report --critical-path reuse + regression gate."""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.fast

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO_ROOT, "scripts")
sys.path.insert(0, SCRIPTS)

import incident_report  # noqa: E402
import perf_report  # noqa: E402


def _span(trace, name, start, end, stage=None, **attrs):
    if stage is not None:
        attrs["stage"] = stage
    return {
        "trace_id": trace,
        "span_id": f"{trace}-{name}-{start}",
        "name": name,
        "start": start,
        "end": end,
        "duration": end - start,
        "attrs": attrs,
        "status": "ok",
    }


def synthetic_spans():
    """One job: 1 s queue wait, 1 s pull, 4 s sample with 1 s of
    encode/submit riding UNDER it (pipelined), 1 s blend, 1 s
    uninstrumented tail. Wall = 8 s."""
    return [
        _span("job-a", "sched.wait", 0.0, 1.0),
        _span("job-a", "tile.pull", 1.0, 2.0, stage="pull"),
        _span("job-a", "tile.sample", 2.0, 6.0, stage="sample"),
        # overlapped I/O: must be credited to sample, not double-counted
        _span("job-a", "tile.submit", 5.0, 6.0, stage="submit"),
        _span("job-a", "tile.blend", 6.0, 7.0, stage="blend"),
        _span("job-a", "cleanup", 7.5, 8.0),  # uninstrumented -> other
    ]


def test_attribution_sums_to_wall_and_priority_resolves_overlap():
    report = incident_report.critical_path(synthetic_spans())
    job = report["jobs"]["job-a"]
    assert job["wall_s"] == pytest.approx(8.0)
    stages = {k: v["seconds"] for k, v in job["stages"].items()}
    assert stages["queue_wait"] == pytest.approx(1.0)
    assert stages["grant_rtt"] == pytest.approx(1.0)
    # the submit second rides UNDER sample: sample keeps its 4 s
    assert stages["sample"] == pytest.approx(4.0)
    assert stages["encode_submit"] == pytest.approx(0.0)
    assert stages["blend"] == pytest.approx(1.0)
    assert stages["other"] == pytest.approx(1.0)
    assert sum(stages.values()) == pytest.approx(job["wall_s"])
    assert job["dominant"] == "sample"
    agg = report["aggregate"]
    assert agg["dominant"] == "sample"
    assert sum(s["seconds"] for s in agg["stages"].values()) == (
        pytest.approx(agg["wall_s"])
    )


def test_multiple_jobs_aggregate_and_unfinished_spans_skip():
    spans = synthetic_spans() + [
        _span("job-b", "tile.pull", 0.0, 3.0, stage="pull"),
        _span("job-b", "tile.sample", 3.0, 4.0, stage="sample"),
        # unfinished span: no end, no duration -> ignored
        {"trace_id": "job-b", "span_id": "x", "name": "tile.encode",
         "start": 4.0, "end": None, "duration": None,
         "attrs": {"stage": "encode"}, "status": "ok"},
    ]
    report = incident_report.critical_path(spans)
    assert set(report["jobs"]) == {"job-a", "job-b"}
    assert report["jobs"]["job-b"]["dominant"] == "grant_rtt"
    assert report["aggregate"]["wall_s"] == pytest.approx(12.0)


def test_bundle_spans_merges_trace_and_flight_deduped():
    trace_spans = synthetic_spans()
    flight_frames = [
        {"type": "span_close", "data": trace_spans[0]},  # duplicate
        {"type": "span_close",
         "data": _span("job-c", "tile.sample", 0.0, 2.0, stage="sample")},
        {"type": "span_close", "data": {"no_trace": True}},  # malformed
    ]
    bundle = {
        "trace": {"trace_id": "job-a", "spans": trace_spans},
        "flight": {"spans": flight_frames},
    }
    spans = incident_report.bundle_spans(bundle)
    assert len(spans) == len(trace_spans) + 1
    report = incident_report.critical_path(spans)
    assert set(report["jobs"]) == {"job-a", "job-c"}


def test_cli_reads_jsonl_and_json_outputs(tmp_path):
    path = tmp_path / "trace.jsonl"
    with open(path, "w") as fh:
        for span in synthetic_spans():
            fh.write(json.dumps(span) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "incident_report.py"),
         str(path), "--json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["aggregate"]["dominant"] == "sample"
    # text mode renders the dominant line
    proc_text = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "incident_report.py"),
         str(path)],
        capture_output=True, text=True,
    )
    assert "dominant" in proc_text.stdout
    assert proc_text.returncode == 0


def test_cli_empty_input_exits_2(tmp_path):
    path = tmp_path / "empty.jsonl"
    path.write_text("")
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "incident_report.py"),
         str(path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2


def test_perf_report_critical_path_column_and_gate():
    spans = synthetic_spans()
    cp = perf_report.critical_path_report(spans)
    assert cp["aggregate"]["dominant"] == "sample"
    rendered = perf_report.render_critical_path(cp)
    assert "dominant sample" in rendered
    # regression gate: grant_rtt share doubling is flagged
    slow_pull = [
        _span("job-a", "sched.wait", 0.0, 1.0),
        _span("job-a", "tile.pull", 1.0, 6.0, stage="pull"),
        _span("job-a", "tile.sample", 6.0, 8.0, stage="sample"),
    ]
    new_cp = perf_report.critical_path_report(slow_pull)
    regressions = perf_report.critical_path_regressions(cp, new_cp, 25.0)
    names = {r["stage"] for r in regressions}
    assert "critical_path:grant_rtt" in names
    # no-change comparison stays quiet
    assert perf_report.critical_path_regressions(cp, cp, 25.0) == []


def test_single_line_jsonl_is_not_mistaken_for_a_bundle(tmp_path):
    """A one-span trace export parses whole as a dict — classification
    must go by bundle markers, not parseability."""
    span = _span("job-solo", "tile.sample", 0.0, 2.0, stage="sample")
    path = tmp_path / "one.jsonl"
    path.write_text(json.dumps(span) + "\n")
    bundle, spans = incident_report.load_document(str(path))
    assert bundle is None
    assert spans == [span]
    report = incident_report.critical_path(spans)
    assert report["jobs"]["job-solo"]["dominant"] == "sample"


def test_critical_path_regressions_render_as_shares_not_seconds():
    cp_old = perf_report.critical_path_report(synthetic_spans())
    slow_pull = [
        _span("job-a", "tile.pull", 0.0, 5.0, stage="pull"),
        _span("job-a", "tile.sample", 5.0, 7.0, stage="sample"),
    ]
    cp_new = perf_report.critical_path_report(slow_pull)
    regressions = perf_report.critical_path_regressions(cp_old, cp_new, 25.0)
    item = next(
        r for r in regressions if r["stage"] == "critical_path:grant_rtt"
    )
    assert item["old_share"] == item["old_p95"]  # honest unit keys
    rendered = perf_report.render_comparison(regressions, 25.0)
    assert "share" in rendered
    assert "critical_path:grant_rtt" in rendered
    assert "s ->" not in rendered  # never formatted as seconds


def test_sweep_scales_to_retention_bound_span_counts():
    """The analyzer must handle a bundle at the retention bounds
    (thousands of spans) in well under a second — the sweep is
    O(n log n), not quadratic in segments x intervals."""
    import time as time_mod

    spans = []
    for i in range(6000):
        stage = ("pull", "sample", "submit", "blend")[i % 4]
        spans.append(
            _span("job-big", f"tile.{stage}", i * 0.01, i * 0.01 + 0.02,
                  stage=stage)
        )
    started = time_mod.perf_counter()
    report = incident_report.critical_path(spans)
    elapsed = time_mod.perf_counter() - started
    assert elapsed < 1.0, f"critical_path took {elapsed:.2f}s for 6k spans"
    job = report["jobs"]["job-big"]
    total = sum(s["seconds"] for s in job["stages"].values())
    assert total == pytest.approx(job["wall_s"])
