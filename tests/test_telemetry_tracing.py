"""Span tracing: nesting, cross-thread trace joining, parenting to the
trace root, bounded retention, JSONL export, deterministic fake clock."""

import json
import threading

from comfyui_distributed_tpu.telemetry import Tracer, get_tracer, reset_tracer
from comfyui_distributed_tpu.resilience.chaos import FakeClock


def test_span_nesting_builds_parent_chain():
    tracer = Tracer()
    with tracer.span("root", trace_id="t1") as root:
        with tracer.span("child") as child:
            with tracer.span("grandchild") as grandchild:
                pass
    assert child.trace_id == "t1"
    assert child.parent_id == root.span_id
    assert grandchild.parent_id == child.span_id
    tree = tracer.tree("t1")
    assert len(tree) == 1
    assert tree[0]["name"] == "root"
    assert tree[0]["children"][0]["children"][0]["name"] == "grandchild"


def test_orphan_spans_parent_to_trace_root():
    """A span created with only a trace id (e.g. a server-side RPC span
    built from the propagated header) connects to the existing root."""
    tracer = Tracer()
    with tracer.span("root", trace_id="t1") as root:
        pass
    with tracer.span("rpc", trace_id="t1") as rpc:
        pass
    assert rpc.parent_id == root.span_id
    assert len(tracer.tree("t1")) == 1


def test_activate_joins_trace_across_threads():
    tracer = Tracer()
    with tracer.span("root", trace_id="t1") as root:
        done = threading.Event()

        def worker():
            token = tracer.activate("t1")
            try:
                with tracer.span("thread_work"):
                    pass
            finally:
                tracer.deactivate(token)
                done.set()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert done.is_set()
    spans = tracer.spans("t1")
    thread_span = next(s for s in spans if s["name"] == "thread_work")
    assert thread_span["parent_id"] == root.span_id


def test_error_status_and_duration():
    clock = FakeClock(step=1.0)
    tracer = Tracer(clock=clock)
    try:
        with tracer.span("boom", trace_id="t1"):
            raise ValueError("nope")
    except ValueError:
        pass
    (span,) = tracer.spans("t1")
    assert span["status"] == "error"
    assert span["attrs"]["error"].startswith("ValueError")
    assert span["duration"] == 1.0  # fake clock: start→end is one step


def test_events_attach_to_active_span():
    tracer = Tracer()
    with tracer.span("root", trace_id="t1"):
        tracer.event("log", message="hello")
    (span,) = tracer.spans("t1")
    assert span["events"][0]["name"] == "log"
    assert span["events"][0]["attrs"]["message"] == "hello"


def test_trace_eviction_bound():
    tracer = Tracer(max_traces=3)
    for i in range(5):
        with tracer.span("s", trace_id=f"t{i}"):
            pass
    assert tracer.trace_ids() == ["t2", "t3", "t4"]
    assert tracer.spans("t0") == []


def test_eviction_is_lru_not_insertion_order():
    """An in-flight execution that keeps producing spans must survive a
    burst of short traces (or hostile trace-id headers) — eviction
    drops the least-recently-USED trace, not the oldest-created."""
    tracer = Tracer(max_traces=3)
    with tracer.span("root", trace_id="active"):
        pass
    for i in range(10):
        with tracer.span("s", trace_id=f"burst{i}"):
            pass
        # the active trace keeps appending spans between bursts
        with tracer.span("tile", trace_id="active"):
            pass
    assert "active" in tracer.trace_ids()
    active = tracer.spans("active")
    assert len(active) == 11  # nothing lost to eviction
    # and the root survived, so the tree stays singly-rooted
    assert len(tracer.tree("active")) == 1


def test_jsonl_export_round_trip(tmp_path):
    tracer = Tracer(clock=FakeClock())
    with tracer.span("root", trace_id="t1", kind="test"):
        with tracer.span("child"):
            pass
    path = tmp_path / "trace.jsonl"
    written = tracer.write_jsonl("t1", str(path))
    assert written == 2
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert {l["name"] for l in lines} == {"root", "child"}
    assert all(l["trace_id"] == "t1" for l in lines)
    assert all(l["end"] is not None for l in lines)


def test_fake_clock_spans_are_deterministic():
    def run():
        tracer = Tracer(clock=FakeClock())
        with tracer.span("a", trace_id="t"):
            with tracer.span("b"):
                pass
            with tracer.span("c"):
                pass
        return [
            (s["name"], s["start"], s["end"]) for s in tracer.spans("t")
        ]

    assert run() == run()


def test_global_tracer_reset():
    t1 = get_tracer()
    assert get_tracer() is t1
    reset_tracer()
    assert get_tracer() is not t1


def test_trace_logger_mirrors_into_spans():
    """trace_info attaches its message as an event on the trace's span
    tree (the subsumption contract of utils/trace_logger.py)."""
    from comfyui_distributed_tpu.utils.trace_logger import trace_info

    tracer = get_tracer()
    with tracer.span("root", trace_id="exec_test_1"):
        pass
    trace_info("exec_test_1", "dispatched")
    (span,) = tracer.spans("exec_test_1")
    assert any(
        e["attrs"].get("message") == "dispatched" for e in span["events"]
    )
    # a trace with no spans stays log-only (no crash, nothing recorded)
    trace_info("exec_never_spanned", "message")
    assert tracer.spans("exec_never_spanned") == []
