"""Deterministic chaos scenarios over the in-process elastic USDU loop.

Each scenario runs master + worker threads against the real JobStore
protocol under a scripted fault plan and asserts the blended output is
BIT-IDENTICAL to the fault-free run (per-tile noise keys fold the
global tile index, so a requeued tile reproduces exactly; see
resilience/chaos.py for the two determinism preconditions).

These are tier-1 tests: CPU-only, stubbed diffusion, a few seconds
each. `pytest -m chaos` selects just this family.
"""

import numpy as np
import pytest

from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

pytestmark = pytest.mark.chaos

# Master pulls are slowed so worker threads deterministically win tiles
# before the master drains the queue — without it the in-process master
# usually finishes everything first and the fault never fires.
SLOW_MASTER = "latency(0.15)@store:pull:master#1-3"


@pytest.fixture(scope="module")
def baseline():
    result = run_chaos_usdu(seed=11)
    assert result.output.shape == (1, 128, 128, 3)
    assert np.isfinite(result.output).all()
    return result.output


def test_fault_free_run_is_reproducible(baseline):
    again = run_chaos_usdu(seed=11)
    np.testing.assert_array_equal(baseline, again.output)


def test_worker_crash_after_pull_recovers_bit_identical(baseline):
    """The acceptance scenario: a worker dies right after pulling a
    tile; the heartbeat-timeout requeue completes the upscale and the
    output matches the fault-free run bit for bit."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
    )
    assert "w1" in result.crashed_workers  # the fault actually fired
    assert "crash" in result.fired_kinds()
    np.testing.assert_array_equal(baseline, result.output)


def test_both_workers_crash_master_covers_everything(baseline):
    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            f"seed=11;{SLOW_MASTER};"
            "crash@chaos:w1:pulled#1;crash@chaos:w2:pulled#1"
        ),
    )
    assert set(result.crashed_workers) == {"w1", "w2"}
    np.testing.assert_array_equal(baseline, result.output)


def test_dropped_heartbeats_cause_requeue_and_duplicate_drop(baseline):
    """Worker w1 stays alive but ALL its heartbeats are swallowed: the
    master times it out and requeues; w1's late submissions are dropped
    as duplicates. Output still bit-identical."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            f"seed=11;{SLOW_MASTER};"
            "drop@store:heartbeat:w1#*;latency(0.8)@chaos:w1:submit#1"
        ),
        worker_timeout=0.4,
    )
    assert "drop" in result.fired_kinds()
    assert result.crashed_workers == []  # alive, just invisible
    np.testing.assert_array_equal(baseline, result.output)


def test_latency_spikes_do_not_change_output(baseline):
    result = run_chaos_usdu(
        seed=11,
        fault_plan="seed=11;latency(0.2)@chaos:w2:pull#1-2;latency(0.1)@store:pull:master#1",
    )
    assert "latency" in result.fired_kinds()
    np.testing.assert_array_equal(baseline, result.output)


def test_store_level_connection_errors_kill_worker_but_not_job(baseline):
    """A connection error at w2's pull RPC takes that worker out (the
    harness treats any injected transport error as fatal to the
    thread); the job still completes identically via the survivors."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};connect_error@chaos:w2:pull#2",
    )
    np.testing.assert_array_equal(baseline, result.output)
