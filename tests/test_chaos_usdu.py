"""Deterministic chaos scenarios over the in-process elastic USDU loop.

Each scenario runs master + worker threads against the real JobStore
protocol under a scripted fault plan and asserts the blended output is
BIT-IDENTICAL to the fault-free run (per-tile noise keys fold the
global tile index, so a requeued tile reproduces exactly; see
resilience/chaos.py for the two determinism preconditions).

These are tier-1 tests: CPU-only, stubbed diffusion, a few seconds
each. `pytest -m chaos` selects just this family.
"""

import numpy as np
import pytest

from comfyui_distributed_tpu.resilience.chaos import run_chaos_usdu

pytestmark = pytest.mark.chaos

# Master pulls are slowed so worker threads deterministically win tiles
# before the master drains the queue — without it the in-process master
# usually finishes everything first and the fault never fires.
SLOW_MASTER = "latency(0.15)@store:pull:master#1-3"


@pytest.fixture(scope="module")
def baseline():
    result = run_chaos_usdu(seed=11)
    assert result.output.shape == (1, 128, 128, 3)
    assert np.isfinite(result.output).all()
    return result.output


def test_fault_free_run_is_reproducible(baseline):
    again = run_chaos_usdu(seed=11)
    np.testing.assert_array_equal(baseline, again.output)


def test_worker_crash_after_pull_recovers_bit_identical(baseline):
    """The acceptance scenario: a worker dies right after pulling a
    tile; the heartbeat-timeout requeue completes the upscale and the
    output matches the fault-free run bit for bit."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
    )
    assert "w1" in result.crashed_workers  # the fault actually fired
    assert "crash" in result.fired_kinds()
    np.testing.assert_array_equal(baseline, result.output)


def test_both_workers_crash_master_covers_everything(baseline):
    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            f"seed=11;{SLOW_MASTER};"
            "crash@chaos:w1:pulled#1;crash@chaos:w2:pulled#1"
        ),
    )
    assert set(result.crashed_workers) == {"w1", "w2"}
    np.testing.assert_array_equal(baseline, result.output)


def test_dropped_heartbeats_cause_requeue_and_duplicate_drop(baseline):
    """Worker w1 stays alive but ALL its heartbeats are swallowed: the
    master times it out and requeues; w1's late submissions are dropped
    as duplicates. Output still bit-identical."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            f"seed=11;{SLOW_MASTER};"
            "drop@store:heartbeat:w1#*;latency(0.8)@chaos:w1:submit#1"
        ),
        worker_timeout=0.4,
    )
    assert "drop" in result.fired_kinds()
    assert result.crashed_workers == []  # alive, just invisible
    np.testing.assert_array_equal(baseline, result.output)


def test_latency_spikes_do_not_change_output(baseline):
    result = run_chaos_usdu(
        seed=11,
        fault_plan="seed=11;latency(0.2)@chaos:w2:pull#1-2;latency(0.1)@store:pull:master#1",
    )
    assert "latency" in result.fired_kinds()
    np.testing.assert_array_equal(baseline, result.output)


def test_straggler_worker_detected_suspected_and_speculated(baseline):
    """The PR-3 acceptance scenario: w1 silently slows to far beyond
    10x the median tile latency (no crash, no missed heartbeat — the
    worker_timeout is far above the injected latency, so PR 1's
    heartbeat requeue can NOT be the recovery path). The watchdog must
    (a) flag w1 as a straggler, (b) transition it to suspect in the
    HealthRegistry, (c) detect the stalled tail and speculatively
    re-dispatch the in-flight orphan, and the final canvas must still
    be bit-identical to the no-fault run (first result wins; duplicate
    submissions drop).

    Determinism construction (no wall-clock races): w2 crash-holds a
    tile, so the job CANNOT complete without the watchdog speculating
    it (the 10s worker_timeout and 20s master deadline are far beyond
    the test's horizon) — the stall verdict has unbounded headroom.
    And because the job stays open until w1's in-flight tiles land,
    every one of w1's slow submits is recorded as a latency sample
    before cleanup — the straggler verdict can't race the shutdown
    (the watchdog's stop() runs a final straggler pass either way)."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            f"seed=11;{SLOW_MASTER};latency(0.4)@chaos:w1:pulled#*;"
            "crash@chaos:w2:pulled#1"
        ),
        worker_timeout=10.0,  # heartbeat requeue never fires
        watchdog={},
    )
    assert "latency" in result.fired_kinds()
    assert result.crashed_workers == ["w2"]
    assert "w1" in result.stragglers, result.stragglers
    assert result.health.get("w1", {}).get("state") == "suspect", result.health
    # the quiet tail triggered speculation of w1's in-flight tile(s)
    assert result.stalls, "stall never detected"
    assert any(result.speculated.values()), result.speculated
    np.testing.assert_array_equal(baseline, result.output)


def test_straggler_fires_and_resolves_latency_slo_alert(baseline):
    """The fleet-observability acceptance scenario: the same injected
    straggler plan as above, with a live burn-rate SLO engine over the
    harness latency stream. The 10x straggler's over-threshold samples
    must FIRE the tile_latency alert while the run is hot; once the
    watchdog quarantines the straggler (suspect -> tail-trimmed out)
    no further bad samples arrive, the short window drains, and the
    alert must RESOLVE — strictly after it fired. Alert plumbing
    changes observability only: the canvas stays bit-identical."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            f"seed=11;{SLOW_MASTER};latency(0.4)@chaos:w1:pulled#*;"
            "crash@chaos:w2:pulled#1"
        ),
        worker_timeout=10.0,  # heartbeat requeue never fires
        watchdog={},
        slo={},
    )
    assert "w1" in result.stragglers
    assert result.health.get("w1", {}).get("state") == "suspect"
    kinds = [a["type"] for a in result.alerts]
    assert kinds == ["alert_fired", "alert_resolved"], result.alerts
    fired, resolved = result.alerts
    assert fired["slo"] == resolved["slo"] == "tile_latency"
    assert resolved["ts"] > fired["ts"]
    assert resolved["active_seconds"] > 0
    assert not result.slo_active
    np.testing.assert_array_equal(baseline, result.output)


def test_slo_engine_stays_quiet_on_a_healthy_run(baseline):
    result = run_chaos_usdu(seed=11, slo={})
    assert result.alerts == []
    assert not result.slo_active
    np.testing.assert_array_equal(baseline, result.output)


def test_stall_speculation_recovers_a_crashed_worker_before_timeout(baseline):
    """w1 crashes after pulling a tile, with a worker timeout so large
    the heartbeat-staleness requeue would take 10s — the watchdog's
    stall detector speculates the orphaned tile within ~0.3s instead,
    and the output is still bit-identical."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
        worker_timeout=10.0,
        watchdog={},
    )
    assert "w1" in result.crashed_workers
    assert result.stalls, "stall never detected"
    speculated = [t for tids in result.speculated.values() for t in tids]
    assert speculated, "no speculative re-dispatch happened"
    np.testing.assert_array_equal(baseline, result.output)


def test_watchdog_stays_quiet_on_a_healthy_run(baseline):
    """No faults: the monitor must not invent stragglers or stalls
    (and must not perturb the output)."""
    result = run_chaos_usdu(seed=11, watchdog={})
    assert result.stragglers == []
    assert result.stalls == []
    assert result.speculated == {}
    np.testing.assert_array_equal(baseline, result.output)


def test_weighted_placement_starves_straggler_but_not_output(baseline):
    """The scheduler-PR acceptance scenario: w1 is a 10x straggler.
    Under cost-aware weighted placement (speed-EWMA batches + tail
    trimming) w1 must be assigned measurably fewer tiles, the policy
    must show its depressed speed ratio (and at least one tail trim),
    and the canvas must stay bit-identical to the fault-free baseline —
    placement changes WHO computes a tile, never WHAT.

    Determinism construction (the PR-7-noted flake, fixed): the share
    assertions used to compare two RACING chaos runs whose claim rates
    came from real `latency(...)` sleeps — under CI load the sleep
    jitter could compress the weighted-vs-uniform margin to zero. The
    fixed test uses the same injectable idiom the other scenarios use:
    the straggler's weights are SCRIPTED into the policy
    (record_latency, the exact stream the latency sink would feed), and
    the share assertion replays a deterministic interleaved pull
    sequence against the real JobStore — every claim count is a pure
    function of the policy model, no wall clock anywhere. The chaos run
    keeps asserting the canvas invariant under the same fault plan."""
    import asyncio

    from comfyui_distributed_tpu.jobs import JobStore
    from comfyui_distributed_tpu.scheduler.placement import PlacementPolicy

    # --- deterministic share: scripted 10x gap, interleaved pulls ----
    policy = PlacementPolicy(
        min_samples=1, base_batch=2, max_batch=4, tail_tiles=2,
        trim_ratio=0.5,
    )
    for _ in range(4):
        policy.record_latency("w1", 0.35)   # the straggler
        policy.record_latency("w2", 0.035)  # the healthy worker

    async def drain_interleaved():
        store = JobStore()
        store.placement = policy
        await store.init_tile_job("job", list(range(16)))
        counts = {"w1": 0, "w2": 0}
        while True:
            claimed = False
            for wid in ("w1", "w2"):
                grant = await store.pull_tasks("job", wid, timeout=0.01)
                for task_id in grant:
                    await store.submit_result("job", wid, task_id, None)
                counts[wid] += len(grant)
                claimed = claimed or bool(grant)
            if not claimed:
                return counts

    counts = asyncio.run(drain_interleaved())
    total = sum(counts.values())
    assert total == 16
    # the straggler's share is far below its uniform half
    assert counts["w1"] < counts["w2"], counts
    assert counts["w1"] <= total // 3, counts
    # the policy saw the slowness and acted
    snap = policy.snapshot()["workers"]["w1"]
    assert snap["speed_ratio"] < 0.5, snap
    assert snap["tail_trims"] >= 1, snap

    # --- the canvas invariant under the same pressure ----------------
    plan = (
        "seed=11;latency(0.2)@store:pull:master#1-8;"
        "latency(0.35)@chaos:w1:pulled#*;latency(0.035)@chaos:w2:pulled#*"
    )
    big_baseline = run_chaos_usdu(seed=11, image_hw=(128, 128))
    weighted = run_chaos_usdu(
        seed=11, image_hw=(128, 128), fault_plan=plan,
        worker_timeout=10.0, pipeline=False,
        placement=dict(
            base_batch=1, max_batch=4, tail_tiles=8,
            min_samples=1, trim_ratio=0.5,
        ),
    )
    np.testing.assert_array_equal(big_baseline.output, weighted.output)


def test_weighted_placement_is_invisible_on_a_healthy_fleet(baseline):
    """No faults + placement enabled: output identical, nobody
    trimmed (uniform cold-start weights keep everyone eligible)."""
    result = run_chaos_usdu(seed=11, placement={})
    np.testing.assert_array_equal(baseline, result.output)
    for stats in result.placement["workers"].values():
        assert stats["tail_trims"] == 0


# --------------------------------------------------------------------------
# batched + pipelined data path (PR-5 tentpole parity + chaos coverage)
# --------------------------------------------------------------------------


def test_batched_pipelined_parity_square_grid(baseline):
    """Acceptance: the batched+pipelined elastic path (K=4 vmapped
    grants, threaded pipeline, pull prefetch) produces a bit-identical
    canvas to the serial per-tile baseline on an exactly-divisible
    grid (4 tiles, K=4)."""
    result = run_chaos_usdu(seed=11, tile_batch=4, pipeline=True, prefetch=True)
    np.testing.assert_array_equal(baseline, result.output)


def test_batched_pipelined_parity_ragged_grid():
    """Acceptance: a ragged grid (15 tiles, K=4 — remainder chunks pad
    to the bucket via wraparound duplicates with folded keys) is
    bit-identical between the serial and batched+pipelined paths."""
    serial = run_chaos_usdu(seed=7, image_hw=(96, 160), pipeline=False)
    batched = run_chaos_usdu(
        seed=7, image_hw=(96, 160), tile_batch=4, pipeline=True
    )
    np.testing.assert_array_equal(serial.output, batched.output)


def test_crash_after_pull_with_pipelined_batched_grants(baseline):
    """Chaos re-run with pipelining + batched grants enabled: a worker
    crashing after pulling (part of) a grant must not orphan tiles —
    the requeue path recovers and the canvas stays bit-identical."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
        tile_batch=4,
        pipeline=True,
    )
    assert "w1" in result.crashed_workers
    np.testing.assert_array_equal(baseline, result.output)


def test_speculative_redispatch_with_pipelined_batched_grants(baseline):
    """The watchdog's speculative re-dispatch under pipelining +
    batched grants: a crashed worker's in-flight tile is speculated
    long before the heartbeat timeout, and the canvas is still
    bit-identical (first result wins, duplicates drop)."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
        worker_timeout=10.0,
        watchdog={},
        tile_batch=4,
        pipeline=True,
    )
    assert "w1" in result.crashed_workers
    assert result.stalls, "stall never detected"
    assert any(result.speculated.values()), "no speculative re-dispatch"
    np.testing.assert_array_equal(baseline, result.output)


def test_device_canvas_fault_free_bit_identical(baseline):
    """On-device compositing (CDT_DEVICE_CANVAS=1) vs the deterministic
    host canvas: same tiles, sorted order on both sides — the output
    must be bit-identical, which is what licenses the one-flush d2h."""
    result = run_chaos_usdu(seed=11, device_canvas=True)
    np.testing.assert_array_equal(baseline, result.output)


def test_device_canvas_crash_recovery_bit_identical(baseline):
    result = run_chaos_usdu(
        seed=11,
        device_canvas=True,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
    )
    assert "w1" in result.crashed_workers
    np.testing.assert_array_equal(baseline, result.output)


def test_device_canvas_speculation_bit_identical(baseline):
    """Speculative re-dispatch lands duplicate tiles out of order; the
    device canvas's last-write-wins buffer plus sorted compositing must
    still match the host baseline exactly."""
    result = run_chaos_usdu(
        seed=11,
        device_canvas=True,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
        worker_timeout=10.0,
        watchdog={},
        tile_batch=4,
        pipeline=True,
    )
    assert "w1" in result.crashed_workers
    assert any(result.speculated.values()), "no speculative re-dispatch"
    np.testing.assert_array_equal(baseline, result.output)


def test_prefetch_crash_requeues_prefetched_grant(baseline):
    """With pull prefetch on, a crashing worker strands BOTH its
    in-flight grant and the prefetched one; heartbeat-timeout requeue
    must recover every tile bit-identically."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#2",
        tile_batch=2,
        pipeline=True,
        prefetch=True,
    )
    np.testing.assert_array_equal(baseline, result.output)


# --------------------------------------------------------------------------
# mesh-parallel tile execution (multi-chip sharded grants)
# --------------------------------------------------------------------------


def _require_devices(n: int) -> None:
    import jax

    if jax.local_device_count() < n:
        pytest.skip(f"needs >= {n} (virtual) devices")


def test_mesh_parity_square_grid(baseline):
    """Acceptance: the 4-participant mesh path (grants sharded across
    the data axis with NamedSharding, gathered via host_collect)
    produces a bit-identical canvas to the 1-device run on an
    exactly-divisible square grid."""
    _require_devices(4)
    result = run_chaos_usdu(
        seed=11, tile_batch=4, pipeline=True, mesh_devices=4
    )
    np.testing.assert_array_equal(baseline, result.output)


def test_mesh_parity_ragged_grid():
    """Acceptance: a ragged grid (15 tiles — sub-bucket chunks pad via
    wraparound duplicates up to multiples of the data-axis width) is
    bit-identical between the serial 1-device path and the 4-device
    mesh path."""
    _require_devices(4)
    serial = run_chaos_usdu(seed=7, image_hw=(96, 160), pipeline=False)
    meshed = run_chaos_usdu(
        seed=7, image_hw=(96, 160), tile_batch=4, pipeline=True,
        mesh_devices=4,
    )
    np.testing.assert_array_equal(serial.output, meshed.output)


def test_mesh_parity_survives_worker_crash(baseline):
    """Mesh-parallel grants + the crash-after-pull requeue path: the
    recovery tile recomputes (possibly on a different participant
    count) and the canvas stays bit-identical."""
    _require_devices(4)
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};crash@chaos:w1:pulled#1",
        tile_batch=4,
        pipeline=True,
        mesh_devices=4,
    )
    assert "w1" in result.crashed_workers
    np.testing.assert_array_equal(baseline, result.output)


# --------------------------------------------------------------------------
# kill-the-master scenarios (durable control plane acceptance)
# --------------------------------------------------------------------------


def test_master_killed_after_pull_recovers_bit_identical(baseline, tmp_path):
    """Acceptance phase 1: the master is killed right after claiming
    work (its 3rd pull RPC). Restart + journal recovery requeues the
    in-flight/volatile tiles, restores durable worker results, and the
    drained canvas is bit-identical to an uninterrupted run."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_master_crash

    # Deterministic construction: the workers' first pulls are held
    # back, so the master instantly claims tile 0 — and is killed at
    # its FIRST submit RPC, i.e. after the pull was journaled but
    # before any completion: the claimed tile is in flight at death
    # and recovery must requeue it.
    result = run_chaos_master_crash(
        seed=11,
        crash_plan=(
            "latency(1.5)@store:pull:w1#1;latency(1.5)@store:pull:w2#1;"
            "crash@store:submit:master#1"
        ),
        journal_dir=str(tmp_path / "wal"),
    )
    assert "crash" in result.fired_kinds()  # the master actually died
    assert result.report["performed"]
    assert result.report["jobs_recovered"] == 1
    assert result.report["tasks_requeued"] >= 1  # the in-flight claim
    np.testing.assert_array_equal(baseline, result.output)


def test_master_killed_after_partial_submit_recovers_bit_identical(
    baseline, tmp_path
):
    """Acceptance phase 2: the master dies mid-submit — after some of
    its own completions were journaled but before the job finished.
    Volatile (master-local) completions are demoted for bit-identical
    recompute; the canvas must still match the uninterrupted run."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_master_crash

    # the workers' first pulls are held back so the master
    # deterministically performs the partial submit the scenario is
    # named for: submit #1 lands in the journal, submit #2 is the kill
    result = run_chaos_master_crash(
        seed=11,
        crash_plan=(
            "latency(1.5)@store:pull:w1#1;latency(1.5)@store:pull:w2#1;"
            "crash@store:submit:master#2"
        ),
        journal_dir=str(tmp_path / "wal"),
    )
    assert "crash" in result.fired_kinds()
    assert result.report["performed"]
    # something real was at stake: recovery either requeued in-flight
    # tiles or restored durable worker results (typically both)
    assert (
        result.report["tasks_requeued"] + result.report["tasks_restored"] > 0
    ), result.report
    np.testing.assert_array_equal(baseline, result.output)


def test_master_crash_recovery_is_idempotent(tmp_path):
    """Replaying the same snapshot+WAL twice yields identical state —
    a recovery interrupted by a second crash simply runs again."""
    from comfyui_distributed_tpu.durability.recovery import (
        verify_idempotent_replay,
    )
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_master_crash

    journal_dir = str(tmp_path / "wal")
    # the master's slowed first pull lets workers win tiles (their
    # durable payloads land in the journal), then its SECOND pull —
    # which every master run is guaranteed to reach — is the kill
    result = run_chaos_master_crash(
        seed=11,
        crash_plan=(
            "latency(0.3)@store:pull:master#1;crash@store:pull:master#2"
        ),
        journal_dir=journal_dir,
    )
    assert "crash" in result.fired_kinds()
    assert verify_idempotent_replay(journal_dir)


def test_store_level_connection_errors_kill_worker_but_not_job(baseline):
    """A connection error at w2's pull RPC takes that worker out (the
    harness treats any injected transport error as fatal to the
    thread); the job still completes identically via the survivors."""
    result = run_chaos_usdu(
        seed=11,
        fault_plan=f"seed=11;{SLOW_MASTER};connect_error@chaos:w2:pull#2",
    )
    np.testing.assert_array_equal(baseline, result.output)


# --------------------------------------------------------------------------
# warm-standby failover (HA layer acceptance)
# --------------------------------------------------------------------------


def _assert_failover_invariants(baseline, result):
    """The acceptance bundle every failover scenario must satisfy:
    crash fired, promotion bumped the epoch, fencing held (zombie
    journal append raised and journaled NOTHING; stale-epoch pull AND
    submit rejected), and the canvas is bit-identical."""
    assert "crash" in result.fired_kinds()
    assert result.epochs[1] == result.epochs[0] + 1
    assert result.zombie_fenced, "ex-active journal append was not fenced"
    assert result.stale_pull_rejected
    assert result.stale_submit_rejected
    assert result.zombie_journaled_records == 0
    assert result.report["jobs_recovered"] == 1
    np.testing.assert_array_equal(baseline, result.output)


def test_failover_after_master_pull_promotes_bit_identical(
    baseline, tmp_path
):
    """Kill point 1: the active master dies right at a pull RPC. The
    live standby replica (journal stream teed under the manager lock)
    takes the expired lease, requeues the in-flight grants —
    including the orphan tile the dying master served in its last
    instant — and the promoted master + re-pointed workers drain the
    job to completion with no process restart anywhere."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_failover

    result = run_chaos_failover(
        seed=11,
        crash_plan="crash@store:pull:master#2",
        journal_dir=str(tmp_path / "wal"),
    )
    _assert_failover_invariants(baseline, result)
    if result.orphan_tile is not None:
        # the deterministic orphan claim proves the requeue path ran
        assert result.report["tasks_requeued"] >= 1


def test_failover_after_partial_submit_promotes_bit_identical(
    baseline, tmp_path
):
    """Kill point 2: the active dies after journaling SOME of its own
    completions. Volatile (master-local) completions demote for
    recompute, durable worker payloads restore — exactly the disk
    recovery transform, minus the disk."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_failover

    # workers' first pulls held back so the master deterministically
    # performs the partial submit the scenario is named for
    result = run_chaos_failover(
        seed=11,
        crash_plan=(
            "latency(1.0)@store:pull:w1#1;latency(1.0)@store:pull:w2#1;"
            "crash@store:submit:master#1"
        ),
        journal_dir=str(tmp_path / "wal"),
    )
    _assert_failover_invariants(baseline, result)
    assert result.report["tasks_requeued"] >= 1  # the demoted volatiles


def test_failover_during_snapshot_cadence_promotes_bit_identical(
    baseline, tmp_path
):
    """Kill point 3: snapshot_every=1 makes a snapshot precede every
    append, so the crash lands inside the snapshot cadence — the
    standby's stream (which never reads snapshots) must be unaffected
    and promotion must still reopen the journal at the replicated
    head."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_failover

    result = run_chaos_failover(
        seed=11,
        crash_plan="crash@store:pull:master#3",
        journal_dir=str(tmp_path / "wal"),
        snapshot_every=1,
    )
    _assert_failover_invariants(baseline, result)


def test_failover_with_push_grants_stays_bit_identical(baseline, tmp_path):
    """The pushed-grant path (placement.notify_grants wired as the
    store's grant notifier on BOTH masters) must survive the same
    failover the pull fallback does — push carries availability, never
    assignment, so it can change timing but never the canvas."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_failover

    result = run_chaos_failover(
        seed=11,
        crash_plan="crash@store:pull:master#2",
        journal_dir=str(tmp_path / "wal"),
        push_grants=True,
    )
    _assert_failover_invariants(baseline, result)


def test_failover_standby_replica_reports_sync_and_lag(tmp_path):
    """The promoted run's replica status must show a completed sync:
    zero record lag at promotion (every teed frame applied) and a
    positive applied count — the same numbers the standby serves on
    GET /distributed/durability while following."""
    from comfyui_distributed_tpu.resilience.chaos import run_chaos_failover

    result = run_chaos_failover(
        seed=11,
        crash_plan="crash@store:pull:master#2",
        journal_dir=str(tmp_path / "wal"),
    )
    assert result.replica["synced"] is True
    assert result.replica["lag_records"] == 0
    assert result.replica["applied_lsn"] >= result.replica["applied_records"] > 0
    assert result.replica["source_epoch"] == result.epochs[0]


def test_straggler_alert_auto_captures_an_incident_bundle(baseline, tmp_path):
    """The incident-plane acceptance scenario (docs/observability.md
    §Incidents): the injected 10x straggler fires tile_latency, the
    IncidentManager's bus tap auto-captures a debug bundle holding the
    FIRING evaluation (the alert's rules ride the trigger context) and
    the straggler's per-worker fleet series, a second identical alert
    inside the debounce window captures NOTHING, and — the invariant
    every chaos scenario re-proves — the canvas stays bit-identical."""
    import json
    import os

    from comfyui_distributed_tpu.telemetry.incidents import validate_bundle

    result = run_chaos_usdu(
        seed=11,
        fault_plan=(
            f"seed=11;{SLOW_MASTER};latency(0.4)@chaos:w1:pulled#*;"
            "crash@chaos:w2:pulled#1"
        ),
        worker_timeout=10.0,  # heartbeat requeue never fires
        watchdog={},
        slo={},
        incidents={"dir": str(tmp_path)},
    )
    assert [a["type"] for a in result.alerts][:1] == ["alert_fired"]
    # exactly one bundle: the alert captured, the debounced re-fire
    # did not
    assert len(result.incidents) == 1, result.incidents
    assert result.incidents[0]["trigger"] == "alert_fired"
    assert result.incident_retrigger == "debounced"
    bundle_path = os.path.join(
        str(tmp_path), result.incidents[0]["id"] + ".json"
    )
    with open(bundle_path, "r", encoding="utf-8") as fh:
        bundle = json.load(fh)
    assert validate_bundle(bundle) == []
    # the firing SLO evaluation rode the trigger context
    assert bundle["trigger"]["key"] == "tile_latency"
    rules = bundle["trigger"]["context"].get("rules")
    assert rules and any(r["firing"] for r in rules), rules
    # the straggler's per-worker fleet series is in the bundle's window
    workers = bundle["fleet"]["history"]["workers"]
    assert "w1" in workers, sorted(workers)
    assert workers["w1"]["fleet_worker_tiles_per_s"], workers["w1"]
    # flight recorder evidence from BEFORE the trigger is retained
    assert bundle["flight"]["enabled"] is True
    assert bundle["flight"]["events"]
    np.testing.assert_array_equal(baseline, result.output)


def test_healthy_run_captures_no_incidents(baseline, tmp_path):
    result = run_chaos_usdu(
        seed=11, slo={}, incidents={"dir": str(tmp_path)}
    )
    assert result.incidents == []
    assert result.incident_retrigger == ""
    np.testing.assert_array_equal(baseline, result.output)
