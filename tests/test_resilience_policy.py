"""RetryPolicy / retry_async: backoff shape, jitter determinism,
deadline budgets, and retryable-exception filtering."""

import asyncio
import random

import pytest

from comfyui_distributed_tpu.resilience.policy import (
    RetryPolicy,
    http_policy,
    poll_ready_policy,
    retry_async,
    work_pull_policy,
)


def run(coro):
    return asyncio.run(coro)


def test_delays_exponential_and_capped():
    policy = RetryPolicy(base_delay=1.0, multiplier=2.0, max_delay=5.0, jitter=0.0)
    assert [policy.delay_for(a) for a in range(5)] == [1.0, 2.0, 4.0, 5.0, 5.0]


def test_jitter_is_bounded_and_seed_deterministic():
    policy = RetryPolicy(base_delay=1.0, multiplier=1.0, max_delay=1.0, jitter=0.25)
    a = [policy.delay_for(0, random.Random(7)) for _ in range(10)]
    b = [policy.delay_for(0, random.Random(7)) for _ in range(10)]
    assert a == b  # same seed, same jitter sequence
    assert all(0.75 <= d <= 1.25 for d in a)


def test_retry_async_retries_then_succeeds():
    calls = []

    async def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("boom")
        return "ok"

    async def no_sleep(_):
        pass

    out = run(
        retry_async(
            flaky, RetryPolicy(max_attempts=5, base_delay=0.01, jitter=0.0),
            sleep=no_sleep,
        )
    )
    assert out == "ok"
    assert len(calls) == 3


def test_retry_async_reraises_last_error_on_exhaustion():
    async def always_fails():
        raise ConnectionError("still down")

    async def no_sleep(_):
        pass

    with pytest.raises(ConnectionError, match="still down"):
        run(
            retry_async(
                always_fails,
                RetryPolicy(max_attempts=3, base_delay=0.01, jitter=0.0),
                sleep=no_sleep,
            )
        )


def test_non_retryable_raises_immediately():
    calls = []

    async def rejects():
        calls.append(1)
        raise ValueError("semantic rejection")

    async def no_sleep(_):
        pass

    with pytest.raises(ValueError):
        run(
            retry_async(
                rejects,
                RetryPolicy(max_attempts=5, base_delay=0.01, jitter=0.0),
                retryable=(ConnectionError,),
                sleep=no_sleep,
            )
        )
    assert len(calls) == 1  # no retries for non-transport failures


def test_deadline_stops_before_overshooting():
    """A retry whose backoff would exceed the overall budget is not
    attempted; the last real failure propagates."""
    calls = []
    fake_now = [0.0]

    async def fails():
        calls.append(1)
        raise ConnectionError("down")

    async def advancing_sleep(d):
        fake_now[0] += d

    policy = RetryPolicy(
        max_attempts=10, base_delay=1.0, multiplier=2.0, max_delay=60.0,
        jitter=0.0, deadline=5.0,
    )
    with pytest.raises(ConnectionError):
        run(
            retry_async(
                fails, policy, sleep=advancing_sleep, clock=lambda: fake_now[0]
            )
        )
    # delays 1+2 fit in 5s; the next (4s) would overshoot -> 3 attempts
    assert len(calls) == 3


def test_on_retry_callback_sees_each_failure():
    seen = []

    async def flaky():
        if len(seen) < 2:
            raise ConnectionError("x")
        return True

    async def no_sleep(_):
        pass

    run(
        retry_async(
            flaky, RetryPolicy(max_attempts=5, base_delay=0.01, jitter=0.0),
            on_retry=lambda attempt, exc, delay: seen.append((attempt, str(exc))),
            sleep=no_sleep,
        )
    )
    assert [a for a, _ in seen] == [0, 1]


def test_canonical_policies_read_env_knobs(monkeypatch):
    from comfyui_distributed_tpu.utils import constants

    monkeypatch.setattr(constants, "REQUEST_RETRY_COUNT", 7)
    monkeypatch.setattr(constants, "WORK_PULL_RETRY_COUNT", 11)
    monkeypatch.setattr(constants, "JOB_READY_POLL_ATTEMPTS", 13)
    assert http_policy().max_attempts == 7
    assert work_pull_policy().max_attempts == 11
    ready = poll_ready_policy()
    assert ready.max_attempts == 13
    assert ready.multiplier == 1.0 and ready.jitter == 0.0  # fixed interval
