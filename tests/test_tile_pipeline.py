"""The staged tile pipeline + bucketed grant sampler in isolation.

Covers the PR-5 acceptance points that don't need the full chaos
harness: the bounded compiled-shape set (at most ceil(log2(K))+1
tile-processor shapes for a whole job of varying grant sizes), the
sample/submit overlap (total wall < serial sum of stage times under an
injected slow transport), heartbeats flowing while a device batch is
in flight, and interrupted in-flight grants requeueing cleanly."""

import math
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from comfyui_distributed_tpu.graph.tile_pipeline import (
    GrantSampler,
    TilePipeline,
)
from comfyui_distributed_tpu.ops.upscale import bucket_for, grant_buckets
from comfyui_distributed_tpu.resilience.faults import FaultInjector


# --------------------------------------------------------------------------
# bucket math
# --------------------------------------------------------------------------


def test_grant_buckets_are_pow2_plus_kmax():
    assert grant_buckets(1) == (1,)
    assert grant_buckets(4) == (1, 2, 4)
    assert grant_buckets(8) == (1, 2, 4, 8)
    assert grant_buckets(6) == (1, 2, 4, 6)
    for k in range(1, 33):
        assert len(grant_buckets(k)) <= math.ceil(math.log2(k) or 1) + 1


def test_bucket_for_rounds_up_and_clamps():
    assert bucket_for(1, 8) == 1
    assert bucket_for(3, 8) == 4
    assert bucket_for(5, 8) == 8
    assert bucket_for(99, 8) == 8
    assert bucket_for(3, 1) == 1


# --------------------------------------------------------------------------
# shape buckets bound the compile count
# --------------------------------------------------------------------------


def _toy_tiles(n=8):
    extracted = jnp.arange(n * 2 * 4 * 4 * 3, dtype=jnp.float32).reshape(
        n, 2, 4, 4, 3
    )
    positions = jnp.zeros((n, 2), jnp.int32)
    return extracted, positions


def test_job_of_varying_grants_compiles_bounded_shapes():
    """Acceptance: every grant size 1..K_max through the sampler
    compiles at most ceil(log2(K_max))+1 distinct tile-processor
    shapes (counted via trace side effects — jit re-traces exactly
    once per new input shape)."""
    k_max = 8
    extracted, positions = _toy_tiles(k_max)
    traces = []

    @jax.jit
    def process(params, tile, key, pos, neg, yx):
        traces.append(tile.shape)  # fires at trace time only
        return tile * 2.0

    sampler = GrantSampler(
        process, None, extracted, jax.random.key(0), positions, None, None,
        k_max=k_max,
    )
    grant_sizes = list(range(1, k_max + 1)) + [5, 3, 7, 2, 8, 1, 6]
    for size in grant_sizes:
        out = sampler.sample(list(range(size)))
        assert out.shape[0] == size
    assert len(traces) <= math.ceil(math.log2(k_max)) + 1, traces
    assert sampler.buckets_used <= set(grant_buckets(k_max))


def test_ragged_grant_pads_with_wraparound_duplicates():
    """A 3-tile grant at K=4 pads to the 4-bucket by wrapping; the
    surplus is sliced off and the kept rows equal the serial result."""
    extracted, positions = _toy_tiles(8)
    sampler = GrantSampler(
        lambda params, tile, key, pos, neg, yx: tile * 3.0,
        None, extracted, jax.random.key(0), positions, None, None, k_max=4,
    )
    out = np.asarray(sampler.sample([5, 6, 7]))
    assert out.shape[0] == 3
    np.testing.assert_array_equal(out, np.asarray(extracted[5:8]) * 3.0)
    assert sampler.padded_tiles == 1


def test_grant_chunks_split_at_kmax():
    extracted, positions = _toy_tiles(8)
    sampler = GrantSampler(
        lambda *a: a[1], None, extracted, jax.random.key(0), positions,
        None, None, k_max=4,
    )
    assert sampler.chunks([0, 1, 2, 3, 4, 5]) == [[0, 1, 2, 3], [4, 5]]
    serial = GrantSampler(
        lambda *a: a[1], None, extracted, jax.random.key(0), positions,
        None, None, k_max=1,
    )
    assert serial.chunks([0, 1, 2]) == [[0], [1], [2]]


def test_warmup_precompiles_the_steady_state_bucket():
    """Warmup (run during the worker's ready-poll window) compiles the
    largest bucket ahead of time: the first real grant of that shape
    triggers no new trace."""
    extracted, positions = _toy_tiles(4)
    traces = []

    @jax.jit
    def process(params, tile, key, pos, neg, yx):
        traces.append(1)
        return tile

    sampler = GrantSampler(
        process, None, extracted, jax.random.key(0), positions, None, None,
        k_max=4,
    )
    sampler.warmup()
    warmed = len(traces)
    assert warmed >= 1
    sampler.sample([0, 1, 2, 3])
    assert len(traces) == warmed  # steady-state shape came from warmup


# --------------------------------------------------------------------------
# pipeline overlap + liveness
# --------------------------------------------------------------------------


def _host_result(idxs):
    return np.zeros((len(idxs), 1, 2, 2, 3), np.float32)


def test_pipeline_overlaps_sample_with_slow_submit():
    """Acceptance: with a FaultInjector-injected slow transport on the
    submit stage, the pipelined wall is measurably below the serial sum
    of stage times — sampling of grant N overlaps the submit of grant
    N-1."""
    sample_s, submit_s, n_grants = 0.12, 0.12, 4
    injector = FaultInjector(
        "seed=0;" + f"latency({submit_s})@pipe:submit#*"
    )
    grants = [[i] for i in range(n_grants)]
    flushed = []

    def pull():
        return grants.pop(0) if grants else None

    def sample(chunk):
        time.sleep(sample_s)  # the "device"
        return _host_result(chunk)

    def flush(final):
        if flushed_pending:
            injector.check_blocking("pipe:submit")
            flushed.extend(flushed_pending)
            flushed_pending.clear()

    flushed_pending: list[int] = []

    pipeline = TilePipeline(
        pull=pull,
        sample=sample,
        emit=lambda t, arr: flushed_pending.append(t),
        flush=flush,
        to_host=lambda r: r,
        role="worker",
        threaded=True,
        prefetch=True,
    )
    started = time.monotonic()
    stats = pipeline.run()
    wall = time.monotonic() - started

    serial_sum = n_grants * (sample_s + submit_s)
    assert stats["tiles"] == n_grants
    assert sorted(flushed) == list(range(n_grants))
    # generous margin (threads + CI jitter), still strictly below the
    # serial stage-time sum — the overlap is real
    assert wall < serial_sum - sample_s / 2, (wall, serial_sum)


def test_heartbeats_flow_while_device_batch_in_flight():
    """Acceptance: a long device batch must not starve liveness — the
    I/O stage emits idle heartbeats while sampling is in flight."""
    beats = []
    first_emit = []

    def sample(chunk):
        time.sleep(0.5)
        return _host_result(chunk)

    grants = [[0]]
    pipeline = TilePipeline(
        pull=lambda: grants.pop(0) if grants else None,
        sample=sample,
        emit=lambda t, arr: first_emit.append(time.monotonic()),
        flush=lambda final: None,
        to_host=lambda r: r,
        heartbeat=lambda: beats.append(time.monotonic()),
        heartbeat_interval=0.05,
        role="worker",
        threaded=True,
        prefetch=False,
    )
    pipeline.run()
    assert first_emit
    idle_beats = [b for b in beats if b < first_emit[0]]
    assert len(idle_beats) >= 3, (len(idle_beats), len(beats))


def test_sync_mode_runs_stages_inline():
    grants = [[0, 1], [2]]
    order = []
    pipeline = TilePipeline(
        pull=lambda: grants.pop(0) if grants else None,
        sample=lambda chunk: (order.append(("sample", tuple(chunk))), _host_result(chunk))[1],
        emit=lambda t, arr: order.append(("emit", t)),
        flush=lambda final: order.append(("flush", final)),
        to_host=lambda r: r,
        role="worker",
        threaded=False,
    )
    stats = pipeline.run()
    assert stats == {"batches": 2, "tiles": 3}
    # flush is consulted after EVERY tile (size thresholds live inside
    # the callback) — a per-batch consult could overshoot the payload
    # budget by K-1 tiles
    assert order == [
        ("sample", (0, 1)), ("emit", 0), ("flush", False),
        ("emit", 1), ("flush", False),
        ("sample", (2,)), ("emit", 2), ("flush", False), ("flush", True),
    ]


# --------------------------------------------------------------------------
# interrupts + error propagation
# --------------------------------------------------------------------------


def test_interrupt_releases_unprocessed_grant_sync():
    """An interrupted in-flight grant requeues cleanly: tiles already
    emitted are flushed, the unprocessed remainder goes to release()."""
    grants = [[0, 1, 2, 3]]
    emitted, released, flushes = [], [], []
    interrupted = threading.Event()

    def check():
        if interrupted.is_set():
            raise InterruptedError("stop")

    def emit(t, arr):
        emitted.append(t)
        if t == 1:
            interrupted.set()

    pipeline = TilePipeline(
        pull=lambda: grants.pop(0) if grants else None,
        sample=lambda chunk: _host_result(chunk),
        chunks=lambda grant: [[t] for t in grant],
        emit=emit,
        flush=lambda final: flushes.append(final),
        to_host=lambda r: r,
        check_interrupted=check,
        release=lambda idxs: released.extend(idxs),
        role="worker",
        threaded=False,
    )
    with pytest.raises(InterruptedError):
        pipeline.run()
    assert emitted == [0, 1]
    assert released == [2, 3]
    assert flushes[-1] is True  # pending results shipped before release


def test_interrupt_release_requeues_into_job_store(server_loop):
    """End to end against the real JobStore: the released remainder of
    an interrupted grant lands back in the pending queue with its
    assignment cleared — no orphaned tiles."""
    from comfyui_distributed_tpu.jobs import JobStore
    from comfyui_distributed_tpu.utils.async_helpers import (
        run_async_in_server_loop,
    )

    store = JobStore()
    run_async_in_server_loop(
        store.init_tile_job("j", list(range(4))), timeout=10
    )
    interrupted = threading.Event()

    def pull():
        batch = run_async_in_server_loop(
            store.pull_tasks("j", "w1", timeout=0.1, limit=4), timeout=10
        )
        if batch:
            # the interrupt lands right after the claim: the whole
            # grant is in flight and unprocessed
            interrupted.set()
        return batch or None

    def check():
        if interrupted.is_set():
            raise InterruptedError("stop")

    pipeline = TilePipeline(
        pull=pull,
        sample=lambda chunk: _host_result(chunk),
        chunks=lambda grant: [[t] for t in grant],
        emit=lambda t, arr: None,
        flush=lambda final: None,
        to_host=lambda r: r,
        check_interrupted=check,
        release=lambda idxs: run_async_in_server_loop(
            store.release_tasks("j", "w1", idxs), timeout=10
        ),
        role="worker",
        threaded=False,
    )
    with pytest.raises(InterruptedError):
        pipeline.run()
    job = run_async_in_server_loop(store.get_tile_job("j"), timeout=10)
    # the claimed-but-unprocessed grant went back: nothing assigned to
    # the worker, every tile pending again
    assert not job.assigned.get("w1"), job.assigned
    assert job.pending.qsize() == 4


def test_io_stage_error_propagates_to_caller():
    grants = [[0], [1], [2]]

    def flush(final):
        raise RuntimeError("submit exploded")

    pipeline = TilePipeline(
        pull=lambda: grants.pop(0) if grants else None,
        sample=lambda chunk: _host_result(chunk),
        emit=lambda t, arr: None,
        flush=flush,
        to_host=lambda r: r,
        role="worker",
        threaded=True,
        prefetch=True,
    )
    with pytest.raises(RuntimeError, match="submit exploded"):
        pipeline.run()
