"""SLO burn-rate engine (telemetry/slo.py) on a fake timeline: the
fast/slow-window interplay, min-events guard, flap suppression, resolve
hysteresis, latency classification, counter-source clamping, and the
transition surfaces (bus event, gauge, history)."""

import asyncio

import pytest

from comfyui_distributed_tpu.telemetry import instruments
from comfyui_distributed_tpu.telemetry.events import get_event_bus
from comfyui_distributed_tpu.telemetry.slo import (
    BurnRule,
    SLOEngine,
    SLOSpec,
    default_slos,
)
from comfyui_distributed_tpu.telemetry.timeseries import SeriesStore

pytestmark = pytest.mark.fast


class Clock:
    def __init__(self, t: float = 1_000_000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, seconds: float) -> None:
        self.t += seconds


def make_engine(clock, *, objective=0.9, threshold=2.0, long_s=300.0,
                short_s=60.0, resolve_hold_s=30.0, min_events=5,
                kind="ratio", threshold_s=None):
    spec = SLOSpec(
        name="t", description="test objective", objective=objective,
        kind=kind, threshold_s=threshold_s,
        rules=(BurnRule(long_s=long_s, short_s=short_s,
                        burn_threshold=threshold),),
        resolve_hold_s=resolve_hold_s, min_events=min_events,
    )
    store = SeriesStore(raw_step=10.0, raw_points=64, clock=clock)
    return SLOEngine(specs=(spec,), store=store, clock=clock)


def feed(engine, clock, steps, bad_every=0, step_s=10.0, n=1):
    """`steps` ticks of `n` events each; every `bad_every`-th tick's
    events are bad (0 = all good)."""
    for i in range(steps):
        bad = bad_every > 0 and i % bad_every == 0
        engine.note_event("t", bad=bad, n=n)
        engine.step()
        clock.advance(step_s)


def test_healthy_traffic_never_fires():
    clock = Clock()
    engine = make_engine(clock)
    feed(engine, clock, steps=40, bad_every=0)
    assert engine.evaluate("t")["firing"] is False
    assert engine.history == type(engine.history)(maxlen=engine.history.maxlen)


def test_sustained_burn_fires_when_both_windows_cross():
    clock = Clock()
    engine = make_engine(clock)  # budget 0.1, burn>=2 -> bad ratio >= 0.2
    feed(engine, clock, steps=12, bad_every=0)      # clean baseline
    feed(engine, clock, steps=12, bad_every=2)      # 50% bad
    verdict = engine.evaluate("t")
    assert verdict["firing"], verdict
    assert engine.is_active("t")
    assert [h["type"] for h in engine.history] == ["alert_fired"]


def test_short_window_alone_does_not_fire():
    """One acute blip inside an otherwise-clean long window: the short
    window burns but the long window (significance) does not — no
    alert. This is exactly what multi-window buys over a naive
    threshold."""
    clock = Clock()
    engine = make_engine(clock, long_s=300.0, short_s=60.0)
    feed(engine, clock, steps=24, bad_every=0, n=5)  # 120 good events
    # an acute burst of bad events in the newest short window: the
    # short ratio crosses, the long ratio (diluted by the clean
    # baseline) does not
    engine.note_event("t", bad=True, n=8)
    engine.step()
    verdict = engine.evaluate("t")
    [rule] = verdict["rules"]
    assert rule["burn_short"] >= rule["threshold"]
    assert rule["burn_long"] < rule["threshold"]
    assert not verdict["firing"]
    assert not engine.is_active("t")


def test_long_window_alone_does_not_fire_after_cause_stops():
    """Burn long enough to scar the long window, then stop: the short
    window recovers first and a NEW alert must not open on the stale
    long-window reading (recency gate)."""
    clock = Clock()
    engine = make_engine(clock, min_events=5)
    feed(engine, clock, steps=12, bad_every=1)  # 100% bad -> fires
    assert engine.is_active("t")
    # cause stops; the short window fills with good traffic while the
    # long window still carries the scar (light traffic, so the scar
    # stays over threshold)
    feed(engine, clock, steps=6, bad_every=0, n=2)
    verdict = engine.evaluate("t")
    [rule] = verdict["rules"]
    assert rule["burn_long"] >= rule["threshold"]  # scar still visible
    assert rule["burn_short"] < rule["threshold"]
    assert not verdict["firing"]


def test_min_events_guard_on_idle_system():
    clock = Clock()
    engine = make_engine(clock, min_events=5)
    # 2 events, both bad: 100% ratio but far under min_events
    engine.note_event("t", bad=True)
    clock.advance(10.0)
    engine.note_event("t", bad=True)
    engine.step()
    assert not engine.is_active("t")


def test_resolve_hysteresis_holds_until_sustained_clear():
    clock = Clock()
    engine = make_engine(clock, resolve_hold_s=30.0)
    feed(engine, clock, steps=12, bad_every=1)
    assert engine.is_active("t")
    # short window still burning right after the cause stops
    feed(engine, clock, steps=2, bad_every=0, n=10)
    assert engine.is_active("t")
    # clear, but not yet for resolve_hold_s
    feed(engine, clock, steps=2, bad_every=0, n=10, step_s=10.0)
    assert engine.is_active("t")
    # sustained clear past the hold resolves
    feed(engine, clock, steps=6, bad_every=0, n=10, step_s=10.0)
    assert not engine.is_active("t")
    assert [h["type"] for h in engine.history] == [
        "alert_fired", "alert_resolved",
    ]
    assert engine.history[-1]["active_seconds"] > 0


def test_flap_suppression_bouncing_burn_resets_the_hold():
    """A boundary bouncing above/below threshold must not ring: every
    re-burn resets the clear timer, so the alert stays OPEN (one alert,
    not N) until a genuinely sustained clear."""
    clock = Clock()
    engine = make_engine(clock, resolve_hold_s=50.0)
    feed(engine, clock, steps=12, bad_every=1)
    assert engine.is_active("t")
    for _ in range(4):  # good... then bad again, repeatedly
        feed(engine, clock, steps=2, bad_every=0, n=10)
        feed(engine, clock, steps=1, bad_every=1, n=10)
    assert engine.is_active("t")
    assert [h["type"] for h in engine.history] == ["alert_fired"]


def test_latency_spec_classifies_against_threshold():
    clock = Clock()
    engine = make_engine(clock, kind="latency", threshold_s=0.5,
                         min_events=2)
    for _ in range(6):
        engine.note_latency("t", 2.0)  # bad
        engine.step()
        clock.advance(10.0)
    assert engine.is_active("t")


def test_set_counts_clamps_counter_regressions():
    clock = Clock()
    engine = make_engine(clock)
    engine.set_counts("t", bad=5, total=100)
    clock.advance(10.0)
    engine.set_counts("t", bad=0, total=3)  # source restarted
    clock.advance(10.0)
    # clamped: no negative deltas anywhere in the windows
    verdict = engine.evaluate("t")
    [rule] = verdict["rules"]
    assert rule["burn_long"] >= 0.0 and rule["burn_short"] >= 0.0


def test_transition_updates_gauge_and_publishes_bus_event():
    async def run():
        sub = get_event_bus().subscribe(
            types={"alert_fired", "alert_resolved"}
        )
        clock = Clock()
        engine = make_engine(clock)
        feed(engine, clock, steps=12, bad_every=1)
        assert engine.is_active("t")
        event = await asyncio.wait_for(sub.get(), timeout=2)
        assert event["type"] == "alert_fired"
        assert event["data"]["slo"] == "t"
        assert event["data"]["rules"][0]["burn_long"] > 0
        assert instruments.alert_active().value(slo="t") == 1.0
        feed(engine, clock, steps=10, bad_every=0, n=10)
        event = await asyncio.wait_for(sub.get(), timeout=2)
        assert event["type"] == "alert_resolved"
        assert instruments.alert_active().value(slo="t") == 0.0

    asyncio.run(run())


def test_default_slos_cover_the_load_bearing_objectives():
    names = {s.name for s in default_slos()}
    assert names == {
        "availability", "tile_latency", "deadline_miss", "journal_latency"
    }
    for spec in default_slos():
        assert 0.0 < spec.objective < 1.0
        assert spec.rules
        if spec.kind == "latency":
            assert spec.threshold_s and spec.threshold_s > 0


def test_status_payload_shape():
    clock = Clock()
    engine = make_engine(clock)
    feed(engine, clock, steps=12, bad_every=1)
    status = engine.status()
    assert status["active"] == ["t"]
    [spec] = status["alerts"]
    assert spec["slo"] == "t" and spec["active"] is True
    assert spec["rules"][0]["long_s"] == 300.0
    assert status["history"][0]["type"] == "alert_fired"
