"""Graceful worker drain on SIGTERM/SIGINT (ISSUE 10 satellite):
``drain_worker`` interrupts the in-flight execution, waits for the
executor to settle, and stops the server; ``register_worker_drain``
installs the handlers only on worker processes."""

import asyncio
import threading

import pytest

from comfyui_distributed_tpu.workers.startup import (
    drain_worker,
    register_worker_drain,
)


class FakeServer:
    def __init__(self):
        self.interrupted = False
        self.stopped = False
        self._executing = threading.Event()

    def interrupt(self):
        self.interrupted = True

    async def stop(self):
        self.stopped = True


def test_drain_worker_interrupts_waits_and_stops():
    async def body():
        server = FakeServer()
        server._executing.set()

        async def finish_soon():
            await asyncio.sleep(0.05)
            server._executing.clear()

        asyncio.get_running_loop().create_task(finish_soon())
        drained = await drain_worker(server, grace_seconds=5.0)
        assert drained
        assert server.interrupted and server.stopped

    asyncio.run(body())


def test_drain_worker_gives_up_after_grace_but_still_stops():
    async def body():
        server = FakeServer()
        server._executing.set()  # never clears: a wedged execution
        drained = await drain_worker(server, grace_seconds=0.2)
        assert not drained
        assert server.interrupted and server.stopped

    asyncio.run(body())


def test_register_worker_drain_is_worker_only(monkeypatch):
    monkeypatch.delenv("CDT_IS_WORKER", raising=False)

    async def body():
        loop = asyncio.get_running_loop()
        calls = []
        monkeypatch.setattr(
            loop, "add_signal_handler", lambda *a, **k: calls.append(a)
        )
        register_worker_drain(loop, FakeServer())
        assert calls == []  # master process: untouched

    asyncio.run(body())


def test_register_worker_drain_installs_handlers_on_workers(monkeypatch):
    monkeypatch.setenv("CDT_IS_WORKER", "1")

    async def body():
        loop = asyncio.get_running_loop()
        installed = {}
        monkeypatch.setattr(
            loop,
            "add_signal_handler",
            lambda sig, cb: installed.setdefault(sig, cb),
        )
        server = FakeServer()
        register_worker_drain(loop, server, grace_seconds=1.0)
        import signal

        assert set(installed) == {signal.SIGINT, signal.SIGTERM}
        # first signal: drain task scheduled (interrupt + stop).
        # loop.stop is shadowed with a recorder ONLY for the drain's
        # duration — run_until_complete itself relies on the real stop.
        stopped = []
        loop.stop = lambda: stopped.append(True)
        try:
            installed[signal.SIGTERM]()
            for _ in range(50):
                await asyncio.sleep(0.01)
                if stopped:
                    break
        finally:
            del loop.stop  # un-shadow the real method
        assert server.interrupted and server.stopped
        assert stopped  # the loop was asked to stop after the drain

    asyncio.run(body())
