"""Baseline file: grandfathered findings, keyed by content fingerprint.

Fingerprints deliberately exclude the line *number* (pure formatting
moves must not churn the baseline) and include an occurrence index (two
identical offending lines in one file baseline independently). Format:

    {
      "version": 1,
      "entries": {
        "<fingerprint>": {
          "code": "CDT001", "path": "...", "line": 12,
          "text": "<stripped source line>",
          "justification": "why this is grandfathered rather than fixed"
        }
      }
    }

Policy (docs/static-analysis.md): the baseline may only shrink. The
runner reports *stale* entries (fingerprints a fresh scan no longer
produces) as failures so fixed findings must be removed from the file,
and ``--update-baseline`` refuses to grow it unless forced.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field

from .core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE_PATH = os.path.join("tools", "cdtlint", "baseline.json")


def fingerprint(finding: Finding, line_text: str, occurrence: int) -> str:
    payload = "\x1f".join(
        [finding.path, finding.code, line_text.strip(), str(occurrence)]
    )
    return hashlib.sha1(payload.encode("utf-8")).hexdigest()[:16]


@dataclass
class Baseline:
    path: str = DEFAULT_BASELINE_PATH
    entries: dict[str, dict] = field(default_factory=dict)

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path=path)
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
        if data.get("version") != BASELINE_VERSION:
            raise ValueError(
                f"baseline {path} has version {data.get('version')!r}; "
                f"expected {BASELINE_VERSION}"
            )
        return cls(path=path, entries=dict(data.get("entries", {})))

    def save(self) -> None:
        # Crash-safe via the shared atomic-write recipe: an interrupted
        # --update-baseline must never leave a truncated baseline that
        # the next CI run would reject as corrupt. The import stays
        # dependency-free (utils.fsio is stdlib-only), preserving the
        # no-pip-install property of the cdt-lint CI job.
        from comfyui_distributed_tpu.utils.fsio import atomic_write_json

        data = {"version": BASELINE_VERSION, "entries": dict(sorted(self.entries.items()))}
        atomic_write_json(self.path, data, indent=2, sort_keys=False)

    def __contains__(self, fp: str) -> bool:
        return fp in self.entries
