"""Scan driver: file discovery, checker dispatch, noqa + baseline filters."""

from __future__ import annotations

import os
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .baseline import Baseline, fingerprint
from .core import FileContext, Finding, ProjectContext, Severity, parse_noqa
from .registry import all_checkers

# What `python scripts/cdt_lint.py` gates by default: the package plus
# the executable entry points. tests/ are exempt (they exercise the
# hazards on purpose); the linter does not lint itself or its fixtures.
DEFAULT_SCAN_PATHS = (
    "comfyui_distributed_tpu",
    "scripts",
    "bench.py",
    "__graft_entry__.py",
)

_EXCLUDE_DIRS = {"__pycache__", "web", ".git", ".cdt"}


def discover_files(root: str, paths: Iterable[str]) -> list[str]:
    """Expand scan paths to repo-relative .py files, sorted (CDT004
    practices what it preaches)."""
    out: set[str] = set()
    for rel in paths:
        abs_path = os.path.join(root, rel)
        if os.path.isfile(abs_path):
            if rel.endswith(".py"):
                out.add(rel.replace(os.sep, "/"))
            continue
        for dirpath, dirnames, filenames in os.walk(abs_path):
            dirnames[:] = sorted(d for d in dirnames if d not in _EXCLUDE_DIRS)
            for fname in filenames:
                if not fname.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fname)
                out.add(os.path.relpath(full, root).replace(os.sep, "/"))
    return sorted(out)


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)  # actionable (gate fails)
    baselined: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    stale_baseline: list[str] = field(default_factory=list)  # fingerprints
    parse_errors: list[str] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline and not self.parse_errors

    def as_json(self) -> dict:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "findings": [f.as_json() for f in self.findings],
            "baselined": [f.as_json() for f in self.baselined],
            "suppressed": [f.as_json() for f in self.suppressed],
            "stale_baseline": list(self.stale_baseline),
            "parse_errors": list(self.parse_errors),
        }


def _line_text(ctx_by_path: dict[str, FileContext], finding: Finding) -> str:
    ctx = ctx_by_path.get(finding.path)
    return ctx.line_text(finding.line) if ctx else ""


def run_lint(
    root: str,
    paths: Optional[Iterable[str]] = None,
    baseline: Optional[Baseline] = None,
    select: Optional[set[str]] = None,
) -> LintResult:
    """Run every registered checker over ``paths`` (repo-relative).

    ``baseline`` entries filter matching findings out of the failure
    set; entries no fresh finding matches are reported as stale.
    ``select`` restricts to a subset of checker codes (tests use this).
    """
    result = LintResult()
    checkers = all_checkers()
    if select is not None:
        checkers = {c: info for c, info in checkers.items() if c in select}

    contexts: list[FileContext] = []
    for rel in discover_files(root, paths or DEFAULT_SCAN_PATHS):
        abs_path = os.path.join(root, rel)
        try:
            with open(abs_path, "r", encoding="utf-8") as fh:
                source = fh.read()
            ctx = FileContext.parse(rel, source)
        except (OSError, SyntaxError, ValueError) as exc:
            result.parse_errors.append(f"{rel}: {exc}")
            continue
        contexts.append(ctx)
    result.files_scanned = len(contexts)
    ctx_by_path = {c.path: c for c in contexts}

    raw: list[Finding] = []
    for ctx in contexts:
        for info in checkers.values():
            if info.scope != "file":
                continue
            raw.extend(info.fn(ctx))
    project = ProjectContext(root=root, files=contexts)
    for info in checkers.values():
        if info.scope == "project":
            raw.extend(info.fn(project))

    raw.sort(key=lambda f: (f.path, f.line, f.col, f.code))

    # noqa suppression (per-file, per-line, optional per-code)
    noqa_by_path = {c.path: parse_noqa(c.lines) for c in contexts}
    survivors: list[Finding] = []
    for f in raw:
        codes = noqa_by_path.get(f.path, {}).get(f.line, "missing")
        if codes is None or (codes != "missing" and f.code in codes):
            result.suppressed.append(f)
        else:
            survivors.append(f)

    # baseline matching: identical (path, code, stripped-line) findings
    # get per-occurrence indices so the fingerprints stay stable
    baseline = baseline or Baseline(path="")
    occurrence: dict[tuple[str, str, str], int] = defaultdict(int)
    matched_fps: set[str] = set()
    for f in survivors:
        text = _line_text(ctx_by_path, f).strip()
        key = (f.path, f.code, text)
        fp = fingerprint(f, text, occurrence[key])
        occurrence[key] += 1
        if fp in baseline:
            matched_fps.add(fp)
            result.baselined.append(f)
        else:
            result.findings.append(f)

    # Stale detection only covers entries a THIS scan could have
    # re-produced: a partial scan (explicit paths, --select) must not
    # report out-of-scope grandfathered entries as stale — and
    # --update-baseline must not silently drop them.
    scanned_paths = set(ctx_by_path)
    active_codes = set(checkers)
    in_scope = {
        fp
        for fp, entry in baseline.entries.items()
        if entry.get("path") in scanned_paths and entry.get("code") in active_codes
    }
    result.stale_baseline = sorted(in_scope - matched_fps)
    return result


def compute_fingerprints(
    root: str,
    result_findings: list[Finding],
    already_baselined: Optional[list[Finding]] = None,
) -> dict[str, dict]:
    """Baseline entries for ``--update-baseline``: re-reads sources to
    recover line text for each finding.

    ``already_baselined`` findings participate in occurrence numbering
    (they did in :func:`run_lint` too) but produce no entries — without
    them, a new finding on a line identical to a baselined one would be
    fingerprinted at occurrence 0, collide with the existing entry, and
    the update would never converge.
    """
    sources: dict[str, list[str]] = {}
    occurrence: dict[tuple[str, str, str], int] = defaultdict(int)
    entries: dict[str, dict] = {}
    new_ids = {id(f) for f in result_findings}
    merged = list(result_findings) + list(already_baselined or [])
    for f in sorted(merged, key=lambda f: (f.path, f.line, f.col, f.code)):
        if f.path not in sources:
            try:
                with open(os.path.join(root, f.path), "r", encoding="utf-8") as fh:
                    sources[f.path] = fh.read().splitlines()
            except OSError:
                sources[f.path] = []
        lines = sources[f.path]
        text = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        key = (f.path, f.code, text)
        fp = fingerprint(f, text, occurrence[key])
        occurrence[key] += 1
        if id(f) not in new_ids:
            continue
        entries[fp] = {
            "code": f.code,
            "path": f.path,
            "line": f.line,
            "text": text,
            "justification": "TODO: justify or fix (baseline policy: shrink-only)",
        }
    return entries


def render_text(result: LintResult, verbose: bool = False) -> str:
    lines: list[str] = []
    for err in result.parse_errors:
        lines.append(f"PARSE ERROR: {err}")
    for f in result.findings:
        lines.append(f.render())
    for fp in result.stale_baseline:
        lines.append(f"STALE BASELINE ENTRY: {fp} (fixed finding still listed; remove it)")
    if verbose:
        for f in result.baselined:
            lines.append(f"baselined: {f.render()}")
        for f in result.suppressed:
            lines.append(f"suppressed: {f.render()}")
    n_err = sum(1 for f in result.findings if f.severity is Severity.ERROR)
    n_warn = len(result.findings) - n_err
    lines.append(
        f"cdt-lint: {result.files_scanned} files scanned, "
        f"{n_err} error(s), {n_warn} warning(s), "
        f"{len(result.baselined)} baselined, {len(result.suppressed)} suppressed, "
        f"{len(result.stale_baseline)} stale baseline entr(y/ies)"
    )
    return "\n".join(lines)


__all__ = [
    "DEFAULT_SCAN_PATHS",
    "LintResult",
    "discover_files",
    "run_lint",
    "render_text",
    "compute_fingerprints",
]
