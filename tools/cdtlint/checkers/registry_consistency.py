"""CDT005: env-knob registry + metric naming consistency (project-wide).

Every ``CDT_*`` environment knob the code reads must be

1. declared in the knob registry
   (``comfyui_distributed_tpu/utils/knob_registry.py``) with a default,
   subsystem, and one-line effect, and
2. documented in the generated ``docs/configuration.md``
   (``python scripts/gen_config_docs.py`` regenerates it).

Registry entries no code reads are flagged as stale so the registry
tracks reality in both directions. Knob *reads* are detected as
whole-string ``CDT_[A-Z0-9_]*`` constants anywhere in scanned code —
this deliberately sees through env-access wrappers like
``constants._env_float("CDT_X", ...)`` that a narrow
``os.environ.get`` matcher would miss.

Metric-name half: every ``registry.counter/gauge/histogram("name",
...)`` literal must be snake_case with the ``cdt_`` prefix; counters
end in ``_total`` and non-counters must not (the conventions
tests/test_telemetry_metrics.py enforces at runtime, moved to lint
time so a bad name fails before a scrape ever happens).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from ..core import Finding, ProjectContext, Severity, call_name
from ..registry import project_checker

KNOB_REGISTRY_PATH = "comfyui_distributed_tpu/utils/knob_registry.py"
CONFIG_DOC_PATH = "docs/configuration.md"

_KNOB_RE = re.compile(r"CDT_[A-Z][A-Z0-9_]*$")
_DOC_KNOB_RE = re.compile(r"CDT_[A-Z][A-Z0-9_]*")
_METRIC_NAME_RE = re.compile(r"^cdt_[a-z][a-z0-9_]*$")

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _iter_knob_reads(ctx) -> Iterator[tuple[str, int]]:
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _KNOB_RE.fullmatch(node.value)
        ):
            yield node.value, node.lineno


def _registry_knobs(ctx) -> dict[str, int]:
    """Knob name -> declaration line, parsed from Knob(...) calls."""
    knobs: dict[str, int] = {}
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and call_name(node) == "Knob"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            knobs[node.args[0].value] = node.lineno
    return knobs


@project_checker(
    "CDT005",
    "registry-consistency",
    "CDT_* env knobs must be declared in the knob registry and documented; "
    "cdt_* metric names must follow the naming conventions",
)
def check_registry_consistency(project: ProjectContext) -> Iterator[Finding]:
    registry_ctx = project.get(KNOB_REGISTRY_PATH)
    if registry_ctx is None:
        yield Finding(
            code="CDT005",
            message=f"knob registry {KNOB_REGISTRY_PATH} is missing from the scan set",
            path=KNOB_REGISTRY_PATH,
            line=1,
            col=0,
            severity=Severity.ERROR,
        )
        return
    declared = _registry_knobs(registry_ctx)

    doc_path = os.path.join(project.root, CONFIG_DOC_PATH)
    documented: set[str] = set()
    doc_exists = os.path.exists(doc_path)
    if doc_exists:
        with open(doc_path, "r", encoding="utf-8") as fh:
            documented = set(_DOC_KNOB_RE.findall(fh.read()))
    else:
        yield Finding(
            code="CDT005",
            message=(
                f"{CONFIG_DOC_PATH} does not exist; run `python scripts/gen_config_docs.py`"
            ),
            path=KNOB_REGISTRY_PATH,
            line=1,
            col=0,
            severity=Severity.ERROR,
        )

    read_sites: dict[str, tuple[str, int]] = {}
    for ctx in project.files:
        if ctx.path == KNOB_REGISTRY_PATH:
            continue
        for knob, lineno in _iter_knob_reads(ctx):
            read_sites.setdefault(knob, (ctx.path, lineno))

    for knob in sorted(read_sites):
        path, lineno = read_sites[knob]
        if knob not in declared:
            yield Finding(
                code="CDT005",
                message=(
                    f"env knob `{knob}` is read here but not declared in "
                    f"{KNOB_REGISTRY_PATH}; add a Knob(...) entry and regenerate "
                    f"{CONFIG_DOC_PATH}"
                ),
                path=path,
                line=lineno,
                col=0,
                severity=Severity.ERROR,
            )
        elif doc_exists and knob not in documented:
            yield Finding(
                code="CDT005",
                message=(
                    f"env knob `{knob}` is declared but missing from {CONFIG_DOC_PATH}; "
                    "run `python scripts/gen_config_docs.py`"
                ),
                path=KNOB_REGISTRY_PATH,
                line=declared[knob],
                col=0,
                severity=Severity.ERROR,
            )

    for knob in sorted(set(declared) - set(read_sites)):
        yield Finding(
            code="CDT005",
            message=(
                f"registry entry `{knob}` is never read by scanned code; "
                "remove the stale Knob(...) declaration"
            ),
            path=KNOB_REGISTRY_PATH,
            line=declared[knob],
            col=0,
            severity=Severity.WARNING,
        )

    # ---- metric naming conventions --------------------------------------
    for ctx in project.files:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute) and func.attr in _METRIC_FACTORIES):
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)):
                continue
            name = node.args[0].value
            if not isinstance(name, str):
                continue
            kind = func.attr
            problems: list[str] = []
            if not _METRIC_NAME_RE.match(name):
                problems.append("must be snake_case with the `cdt_` prefix")
            if kind == "counter" and not name.endswith("_total"):
                problems.append("counter names must end in `_total`")
            if kind in {"gauge", "histogram"} and name.endswith("_total"):
                problems.append(f"{kind} names must not end in `_total`")
            for problem in problems:
                yield Finding(
                    code="CDT005",
                    message=f"metric name `{name}` ({kind}): {problem}",
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    severity=Severity.ERROR,
                )
