"""CDT007: host synchronization in the device-resident hot path.

The device-resident hot path (buffer donation, persistent latents,
on-device canvas) exists to keep the per-step loop off the host: its
win condition is a measured drop in ``cdt_host_tax_ratio`` and d2h
bytes/tile. That win erodes silently the moment someone adds an
``np.asarray`` on a device array (an implicit ``__array__`` d2h pull),
a ``.block_until_ready()`` outside a ledger bracket, or a
``jax.device_get`` inside the dispatch path — each one is a host sync
the transfer ledger never sees and perf_report cannot attribute.

This checker runs only on the hot-path modules (``HOT_PATH_PATHS``):
``ops/stepwise.py`` (the per-step sampler seam),
``graph/batch_executor.py`` (cross-job dispatch/retire), and
``graph/tile_pipeline.py`` (elastic sampling/readback stages). The
sanctioned readback sites — checkpoint spills, the canvas flush, the
ledger-bracketed ``collect``/``to_host`` stages — carry
``# cdt: noqa[CDT007]`` so the ONLY host pulls in these files are the
ones the ledger accounts for.

Checks:

- ``np.asarray`` / ``np.array`` / ``np.ascontiguousarray`` /
  ``np.stack`` / ``np.concatenate`` calls (each forces ``__array__``
  on a device operand — a blocking d2h);
- ``jax.device_get(...)`` (an explicit d2h);
- ``.block_until_ready()`` calls, whether method
  (``x.block_until_ready()``) or functional
  (``jax.block_until_ready(x)``) — a host sync barrier;
- ``ensure_numpy(...)`` (the repo's own materialization helper).
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterator

from ..core import FileContext, Finding, Severity, call_name
from ..registry import checker

# The dispatch-path modules the device-resident guarantee covers.
# Additions here are deliberate API, not a config knob (the
# DETERMINISM_PATHS idiom).
HOT_PATH_PATHS = (
    "comfyui_distributed_tpu/ops/stepwise.py",
    "comfyui_distributed_tpu/graph/batch_executor.py",
    "comfyui_distributed_tpu/graph/tile_pipeline.py",
)

# Calls that force an implicit __array__ materialization (blocking d2h
# when handed a device array).
_HOST_PULL_CALLS = {
    "np.asarray", "numpy.asarray",
    "np.array", "numpy.array",
    "np.ascontiguousarray", "numpy.ascontiguousarray",
    "np.stack", "numpy.stack",
    "np.concatenate", "numpy.concatenate",
    "jax.device_get",
}

# Attribute-call names that are host syncs regardless of receiver.
_HOST_SYNC_METHODS = {"block_until_ready"}

# The repo's own materialization helper (utils/image.ensure_numpy):
# matched by trailing attribute so both `ensure_numpy(x)` and
# `img_utils.ensure_numpy(x)` are caught.
_MATERIALIZE_HELPERS = {"ensure_numpy"}


def applies_to(path: str) -> bool:
    return any(fnmatch.fnmatch(path, pat) for pat in HOT_PATH_PATHS)


@checker(
    "CDT007",
    "host-sync-hot-path",
    "np.asarray / block_until_ready / device_get host pulls inside the "
    "device-resident dispatch-path modules (sanctioned ledger-bracketed "
    "readback sites carry `# cdt: noqa[CDT007]`)",
)
def check_host_sync(ctx: FileContext) -> Iterator[Finding]:
    if not applies_to(ctx.path):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else None
        bare = node.func.id if isinstance(node.func, ast.Name) else None
        if name in _HOST_PULL_CALLS:
            yield Finding(
                code="CDT007",
                message=(
                    f"`{name}(...)` forces a host materialization "
                    "(implicit `__array__` d2h) in the device-resident hot "
                    "path; route readbacks through a ledger-bracketed seam "
                    "or mark the sanctioned site `# cdt: noqa[CDT007]`"
                ),
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                severity=Severity.ERROR,
            )
            continue
        if attr in _HOST_SYNC_METHODS or bare in _HOST_SYNC_METHODS:
            yield Finding(
                code="CDT007",
                message=(
                    "`block_until_ready()` is a host sync barrier in the "
                    "device-resident hot path; only ledger-bracketed timing "
                    "sites may sync (mark them `# cdt: noqa[CDT007]`)"
                ),
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                severity=Severity.ERROR,
            )
            continue
        if attr in _MATERIALIZE_HELPERS or bare in _MATERIALIZE_HELPERS:
            yield Finding(
                code="CDT007",
                message=(
                    "`ensure_numpy(...)` materializes a device array "
                    "host-side in the device-resident hot path; sanctioned "
                    "readback seams carry `# cdt: noqa[CDT007]`"
                ),
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                severity=Severity.ERROR,
            )
