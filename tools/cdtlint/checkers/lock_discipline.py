"""CDT002: lock discipline across the thread/event-loop boundary.

Two hazard shapes, both live in this codebase's mixed asyncio +
worker-thread architecture (~20 lock sites across scheduler / jobs /
resilience / telemetry):

1. A ``threading.Lock`` held across an ``await``: while the coroutine
   is suspended the lock stays held, so any *thread* contending for it
   blocks for an unbounded number of loop iterations — and if a
   same-loop coroutine contends, the loop deadlocks outright.

2. An ``asyncio.Lock`` (or Condition/Semaphore) touched from a sync
   function: ``with lock:`` / ``lock.acquire()`` without ``await``
   either raises at runtime or silently creates an un-awaited
   coroutine; asyncio primitives also bind to whichever loop first
   awaits them (the exact trap ``utils/config.py`` documents dodging).

Lock identity is resolved lexically per file via
:func:`~tools.cdtlint.core.collect_lock_names` — a name must be
*assigned* a lock factory somewhere in the file to participate, so
plain context managers (spans, fault scopes) never false-positive.
``.locked()`` probes are read-only and exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import (
    FileContext,
    Finding,
    Severity,
    collect_lock_names,
    lock_ref_name,
    walk_scope,
)
from ..registry import checker

_READONLY_METHODS = {"locked"}


def _with_item_lock(item: ast.withitem, lock_names: set[str]) -> Optional[str]:
    expr = item.context_expr
    # `with lock:` or `with self._lock:`
    name = lock_ref_name(expr)
    if name in lock_names:
        return name
    return None


def _contains_await(body: list[ast.stmt]) -> Optional[ast.AST]:
    for stmt in body:
        for node in walk_scope(stmt, skip_nested_functions=True):
            if isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return node
        if isinstance(stmt, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
            return stmt
    return None


@checker(
    "CDT002",
    "lock-discipline",
    "threading.Lock held across `await`; asyncio.Lock touched from sync code",
)
def check_lock_discipline(ctx: FileContext) -> Iterator[Finding]:
    threading_locks, asyncio_locks = collect_lock_names(ctx.tree)
    if not threading_locks and not asyncio_locks:
        return

    for fn in ast.walk(ctx.tree):
        if isinstance(fn, ast.AsyncFunctionDef):
            # hazard 1: sync `with <threading lock>:` whose body awaits
            for node in walk_scope(fn):
                if not isinstance(node, ast.With):
                    continue
                for item in node.items:
                    lock = _with_item_lock(item, threading_locks)
                    if lock is None:
                        continue
                    awaited = _contains_await(node.body)
                    if awaited is not None:
                        yield Finding(
                            code="CDT002",
                            message=(
                                f"threading lock `{lock}` held across `await` in "
                                f"`async def {fn.name}` (suspension point at line "
                                f"{getattr(awaited, 'lineno', '?')}); release before "
                                "awaiting, or use an asyncio.Lock owned by this loop"
                            ),
                            path=ctx.path,
                            line=node.lineno,
                            col=node.col_offset,
                            severity=Severity.ERROR,
                        )
        elif isinstance(fn, ast.FunctionDef):
            # hazard 2: asyncio primitives from sync code
            for node in walk_scope(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        lock = _with_item_lock(item, asyncio_locks)
                        if lock is not None:
                            yield Finding(
                                code="CDT002",
                                message=(
                                    f"sync `with {lock}:` on an asyncio lock in "
                                    f"`def {fn.name}`; asyncio locks require "
                                    "`async with` from a coroutine on their owning loop"
                                ),
                                path=ctx.path,
                                line=node.lineno,
                                col=node.col_offset,
                                severity=Severity.ERROR,
                            )
                elif isinstance(node, ast.Call):
                    func = node.func
                    if (
                        isinstance(func, ast.Attribute)
                        and func.attr not in _READONLY_METHODS
                        and func.attr in {"acquire", "release", "notify", "notify_all", "wait"}
                        and lock_ref_name(func.value) in asyncio_locks
                    ):
                        yield Finding(
                            code="CDT002",
                            message=(
                                f"asyncio lock `.{func.attr}()` from sync "
                                f"`def {fn.name}`; only coroutines on the owning loop "
                                "may touch asyncio synchronization primitives"
                            ),
                            path=ctx.path,
                            line=node.lineno,
                            col=node.col_offset,
                            severity=Severity.ERROR,
                        )
