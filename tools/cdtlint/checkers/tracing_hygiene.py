"""CDT003: host-sync / Python-entropy operations inside traced code.

``jax.jit`` / ``jax.vmap`` trace a function once with abstract values;
anything that forces a concrete value (``.item()``, ``float()``,
``np.asarray``, ``block_until_ready``) either crashes with a tracer
error at first call or — worse — silently bakes a Python-side value
into the compiled program (the tracer-leak class PR 2 fixed by hand in
``ops/samplers.py``). Python ``random`` / wall-clock reads inside a
traced function run once at trace time and freeze, breaking both
correctness and the bit-identical-canvas guarantee.

A function counts as *traced* when it is

- decorated with ``jax.jit`` / ``jax.vmap`` / ``partial(jax.jit, ...)``
  (any ``functools.partial`` whose first argument is a jit/vmap name), or
- referenced by name as the first argument of a ``jax.jit(...)`` /
  ``jax.vmap(...)`` call anywhere in the same file, or
- a ``def`` or ``lambda`` nested inside a traced function.

Escape hatches such as ``jax.debug.print`` / ``jax.debug.callback`` /
``jax.pure_callback`` / ``io_callback`` are the sanctioned ways to
reach the host and are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Union

from ..core import FileContext, Finding, Severity, call_name, dotted_name, imported_modules
from ..registry import checker

_JIT_WRAPPERS = {"jax.jit", "jit", "jax.vmap", "vmap", "jax.pmap", "pmap"}
_PARTIAL_NAMES = {"partial", "functools.partial"}

# dotted call name -> why it's hostile inside a trace
_HOST_SYNC_CALLS = {
    "np.asarray": "forces device->host sync; use jnp inside traced code",
    "np.array": "forces device->host sync; use jnp inside traced code",
    "numpy.asarray": "forces device->host sync; use jnp inside traced code",
    "numpy.array": "forces device->host sync; use jnp inside traced code",
    "jax.device_get": "forces device->host sync inside a trace",
    "time.time": "wall clock freezes at trace time; thread it in as an argument",
    "time.monotonic": "wall clock freezes at trace time; thread it in as an argument",
    "time.perf_counter": "wall clock freezes at trace time; thread it in as an argument",
    "datetime.now": "wall clock freezes at trace time; thread it in as an argument",
    "datetime.datetime.now": "wall clock freezes at trace time; thread it in as an argument",
    "print": "runs once at trace time; use jax.debug.print",
}

_HOST_SYNC_METHODS = {
    "item": "concretizes a tracer (host sync / tracer error)",
    "tolist": "concretizes a tracer (host sync / tracer error)",
    "block_until_ready": "host sync inside a trace",
}

_CONCRETIZING_BUILTINS = {"float", "int", "bool"}

Traceable = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


def _is_jit_expr(node: ast.AST) -> bool:
    """Decorator / call expressions that mean `this wraps into a trace`:
    ``jax.jit``, ``jax.vmap``, ``partial(jax.jit, ...)``, and calls of
    those (``partial(jax.jit, static_argnames=...)`` used as decorator)."""
    if isinstance(node, (ast.Name, ast.Attribute)):
        return dotted_name(node) in _JIT_WRAPPERS
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in _JIT_WRAPPERS:
            return True
        if fname in _PARTIAL_NAMES and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _collect_traced_names(tree: ast.Module) -> set[str]:
    """Function names passed (directly or via partial) to jit/vmap calls."""
    traced: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted_name(node.func)
        target_args: list[ast.expr] = []
        if fname in _JIT_WRAPPERS and node.args:
            target_args.append(node.args[0])
        elif fname in _PARTIAL_NAMES and len(node.args) >= 2 and _is_jit_expr(node.args[0]):
            target_args.append(node.args[1])
        for arg in target_args:
            if isinstance(arg, ast.Name):
                traced.add(arg.id)
    return traced


def _iter_traced_functions(tree: ast.Module) -> Iterator[Traceable]:
    traced_names = _collect_traced_names(tree)
    # Lambdas passed inline to jit/vmap are traced too.
    inline_lambdas: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_jit_expr(node):
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    inline_lambdas.add(id(arg))
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(dec) for dec in node.decorator_list):
                yield node
            elif node.name in traced_names:
                yield node
        elif isinstance(node, ast.Lambda) and id(node) in inline_lambdas:
            yield node


def _static_argnames(fn: Traceable) -> set[str]:
    """Names listed in ``static_argnames=(...)`` of a jit decorator on
    ``fn``: those parameters are concrete Python values at trace time,
    so concretizing them is sanctioned."""
    static: set[str] = set()
    decorators = fn.decorator_list if not isinstance(fn, ast.Lambda) else []
    for dec in decorators:
        if not (isinstance(dec, ast.Call) and _is_jit_expr(dec)):
            continue
        for kw in dec.keywords:
            if kw.arg in {"static_argnames", "static_argnums"} and isinstance(
                kw.value, (ast.Tuple, ast.List)
            ):
                for elt in kw.value.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                        static.add(elt.value)
    return static


def _param_names(fn: Traceable) -> set[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _body_of(fn: Traceable) -> list[ast.AST]:
    if isinstance(fn, ast.Lambda):
        return [fn.body]
    return list(fn.body)


@checker(
    "CDT003",
    "jax-tracing-hygiene",
    "host-sync ops and Python entropy inside jit/vmap-traced functions",
)
def check_tracing_hygiene(ctx: FileContext) -> Iterator[Finding]:
    mods = imported_modules(ctx.tree)
    python_random = "random" if "random" in mods else None

    seen: set[int] = set()
    for fn in _iter_traced_functions(ctx.tree):
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        fn_label = fn.name if not isinstance(fn, ast.Lambda) else "<lambda>"
        # Traced (abstract) values enter through the non-static
        # parameters; closure constants and static_argnames parameters
        # are concrete at trace time, so float()/int() on them is the
        # sanctioned hoist-a-constant pattern, not a tracer leak.
        traced_params = _param_names(fn) - _static_argnames(fn)
        stack: list[ast.AST] = _body_of(fn)
        while stack:
            node = stack.pop()
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            reason: Optional[str] = None
            if name in _HOST_SYNC_CALLS:
                reason = _HOST_SYNC_CALLS[name]
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHODS
            ):
                name = f"*.{node.func.attr}"
                reason = _HOST_SYNC_METHODS[node.func.attr]
            elif (
                name in _CONCRETIZING_BUILTINS
                and node.args
                and _root_name(node.args[0]) in traced_params
            ):
                reason = "concretizes a traced parameter (tracer error / silent constant-bake)"
            elif (
                python_random
                and name
                and name.startswith("random.")
                and not name.startswith("random.fold_in")
            ):
                reason = (
                    "Python RNG runs once at trace time and freezes; "
                    "use jax.random with an explicit threaded key"
                )
            if reason:
                yield Finding(
                    code="CDT003",
                    message=f"`{name}(...)` inside traced `{fn_label}`: {reason}",
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    severity=Severity.ERROR,
                )
