"""CDT004: ordering / entropy hygiene in bit-identical-guarantee modules.

The chaos harness asserts the blended canvas is *bit-identical* no
matter which worker produced which tile in which order. That guarantee
dies quietly the moment an ordering-sensitive module iterates a ``set``
(arrival-ordered float blends differ in the last ulp), lists a
directory in readdir order, or derives seed material from the wall
clock. This checker runs only on the modules that back the guarantee
(see ``DETERMINISM_PATHS``) so the rest of the codebase can use sets
freely.

Checks:

- iterating a set expression (literal, ``set(...)``/``frozenset(...)``
  call, set comprehension, set-algebra binop, or a local name assigned
  one) in a ``for`` / comprehension without wrapping it in ``sorted()``;
- ``os.listdir`` / ``os.scandir`` / ``glob.glob`` / ``Path.iterdir`` /
  ``.glob()`` results consumed without ``sorted()``;
- Python global-RNG entropy (``random.random()``, bare
  ``random.seed()``, ``np.random.*``) — all randomness here must flow
  from explicit, threaded ``jax.random`` keys;
- wall-clock values (``time.time()``, ``datetime.now()``) passed to
  seed/key-deriving calls.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Iterator, Optional

from ..core import FileContext, Finding, Severity, call_name, dotted_name, imported_modules
from ..registry import checker

# The modules whose ordering backs the bit-identical canvas guarantee.
# Additions here are deliberate API: widening the net is a reviewed
# change, not a config knob.
DETERMINISM_PATHS = (
    "comfyui_distributed_tpu/ops/tiles.py",
    "comfyui_distributed_tpu/ops/upscale.py",
    "comfyui_distributed_tpu/graph/tile_pipeline.py",
    "comfyui_distributed_tpu/graph/usdu_elastic.py",
    "comfyui_distributed_tpu/jobs/store.py",
    "comfyui_distributed_tpu/resilience/chaos.py",
    # the durable control plane: journal replay and snapshot
    # serialization must be pure functions of on-disk bytes — readdir
    # order, set iteration, or ambient entropy here would make
    # recovery non-reproducible (the idempotent-replay guarantee)
    "comfyui_distributed_tpu/durability/*.py",
    # the mesh tier is now on the production hot path (mesh-parallel
    # GrantSampler dispatches, host_collect gathers, participant seed
    # folding): ordering or ambient entropy here would break the
    # N-device vs 1-device bit-identical canvas guarantee
    "comfyui_distributed_tpu/parallel/*.py",
    # the promotion path: a standby's takeover transform must be a pure
    # function of the replicated frame sequence — ambient entropy or
    # ordering here would break the failover bit-identity guarantee
    # (replication itself, durability/replicate.py, rides the
    # durability/*.py glob above). Lease-expiry arithmetic against the
    # wall-clock lease file is the one sanctioned clock read and is
    # noqa'd at its call sites.
    "comfyui_distributed_tpu/api/standby.py",
    # the cross-job continuous-batching tier is production hot path:
    # batch composition order, checkpoint adoption, and the stepwise
    # sampler seam all back the mixed-batch / preempt-resume
    # bit-identity guarantee — unsorted iteration or ambient entropy
    # here would make a tile's output depend on its batch-mates
    "comfyui_distributed_tpu/graph/batch_executor.py",
    "comfyui_distributed_tpu/ops/stepwise.py",
    "comfyui_distributed_tpu/scheduler/preempt.py",
    # the usage-metering plane: attribution order must be a pure
    # function of the dispatch slot sequence, and every exported
    # mapping must be sorted, or two replays of the same dispatch
    # stream would produce different rollups (billing surfaces must be
    # replay-stable — the conservation identity is only auditable if
    # the numbers it sums are)
    "comfyui_distributed_tpu/telemetry/usage.py",
    # the transfer ledger / profiler capture plane: capture ids and
    # every exported mapping must be pure functions of the observation
    # sequence (injectable clock only — wall-clock in keys or readdir
    # order in the seq scan would make two identical runs produce
    # different waterfalls, breaking the conservation audit)
    "comfyui_distributed_tpu/telemetry/profiling.py",
    # the adapter plane: operand build order, target-map iteration, and
    # catalog scans feed the batch signature and the tile cache key —
    # unsorted iteration or ambient entropy here would make two builds
    # of the SAME adapter plan produce different operands/signatures,
    # breaking both the slot-isolation bit-identity guarantee and
    # cache-key stability
    "comfyui_distributed_tpu/adapters/*.py",
)

_LISTING_CALLS = {"os.listdir", "os.scandir", "glob.glob", "glob.iglob"}
_LISTING_METHODS = {"iterdir", "glob", "rglob"}

_GLOBAL_RNG_PREFIXES = ("random.", "np.random.", "numpy.random.")
_SEEDY_CALL_FRAGMENTS = ("seed", "fold_in", "prngkey", "key")
_WALL_CLOCK_CALLS = {"time.time", "time.time_ns", "datetime.now", "datetime.datetime.now"}


def applies_to(path: str) -> bool:
    return any(fnmatch.fnmatch(path, pat) for pat in DETERMINISM_PATHS)


def _is_set_expr(node: ast.AST, local_sets: set[str]) -> bool:
    if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Call) and call_name(node) in {"set", "frozenset"}:
        return True
    if isinstance(node, ast.Name) and node.id in local_sets:
        return True
    if isinstance(node, ast.BinOp) and isinstance(
        node.op, (ast.Sub, ast.BitAnd, ast.BitOr, ast.BitXor)
    ):
        # set algebra: either side syntactically a set taints the result
        return _is_set_expr(node.left, local_sets) or _is_set_expr(node.right, local_sets)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in {"union", "intersection", "difference", "symmetric_difference"}:
            return _is_set_expr(node.func.value, local_sets)
    return False


def _collect_local_sets(tree: ast.Module) -> set[str]:
    """Names assigned a syntactic set anywhere in the file. Coarse on
    purpose: one module, one meaning per name is the local style."""
    names: set[str] = set()
    non_set_names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
            if isinstance(target, ast.Name):
                if _is_set_expr(node.value, set()):
                    names.add(target.id)
                else:
                    non_set_names.add(target.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            ann = node.annotation
            ann_name = dotted_name(ann) if not isinstance(ann, ast.Subscript) else (
                dotted_name(ann.value)
            )
            if ann_name in {"set", "Set", "typing.Set", "frozenset"}:
                names.add(node.target.id)
    # a name rebound to something non-set anywhere is ambiguous: drop it
    return names - non_set_names


def _iteration_targets(tree: ast.Module) -> Iterator[tuple[ast.AST, str]]:
    """(iterable-expression, context-label) pairs for for-loops and
    comprehensions."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield node.iter, "for loop"
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            for gen in node.generators:
                yield gen.iter, "comprehension"


def _unwrap_enumerate(expr: ast.AST) -> ast.AST:
    if isinstance(expr, ast.Call) and call_name(expr) in {"enumerate", "reversed", "list", "tuple"}:
        if expr.args:
            return _unwrap_enumerate(expr.args[0])
    return expr


@checker(
    "CDT004",
    "determinism",
    "unsorted set/filesystem iteration and wall-clock seed material in "
    "bit-identical-guarantee modules",
)
def check_determinism(ctx: FileContext) -> Iterator[Finding]:
    if not applies_to(ctx.path):
        return
    local_sets = _collect_local_sets(ctx.tree)
    # `random.*` only means the stdlib global RNG when the file itself
    # does `import random` (a `from jax import random` alias must not
    # false-positive on fold_in/PRNGKey).
    has_stdlib_random = "random" in imported_modules(ctx.tree)
    # every node lexically inside any `sorted(...)` call, computed once
    sorted_interior: set[int] = set()
    for n in ast.walk(ctx.tree):
        if isinstance(n, ast.Call) and call_name(n) == "sorted":
            for inner in ast.walk(n):
                sorted_interior.add(id(inner))

    for iter_expr, label in _iteration_targets(ctx.tree):
        expr = _unwrap_enumerate(iter_expr)
        if isinstance(expr, ast.Call) and call_name(expr) == "sorted":
            continue
        if _is_set_expr(expr, local_sets):
            yield Finding(
                code="CDT004",
                message=(
                    f"{label} iterates a set without `sorted()`: iteration order is "
                    "hash-seed dependent and breaks the bit-identical blend order"
                ),
                path=ctx.path,
                line=iter_expr.lineno,
                col=iter_expr.col_offset,
                severity=Severity.ERROR,
            )

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        # directory listings must be consumed through sorted(...)
        is_listing = name in _LISTING_CALLS or (
            isinstance(node.func, ast.Attribute) and node.func.attr in _LISTING_METHODS
        )
        if is_listing:
            if id(node) not in sorted_interior:
                yield Finding(
                    code="CDT004",
                    message=(
                        f"`{name or node.func.attr}(...)` result used without `sorted()`: "
                        "filesystem enumeration order is platform-dependent"
                    ),
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    severity=Severity.ERROR,
                )
            continue
        # Python global RNG
        if (
            name
            and name.startswith(_GLOBAL_RNG_PREFIXES)
            and (has_stdlib_random or not name.startswith("random."))
            and not name.startswith(
                ("random.Random", "np.random.Generator", "numpy.random.Generator",
                 "np.random.default_rng", "numpy.random.default_rng")
            )
        ):
            yield Finding(
                code="CDT004",
                message=(
                    f"`{name}(...)` uses ambient global RNG state; all entropy in this "
                    "module must flow from explicit jax.random keys"
                ),
                path=ctx.path,
                line=node.lineno,
                col=node.col_offset,
                severity=Severity.ERROR,
            )
            continue
        # wall clock as seed material
        callee = (name or "").lower()
        if any(frag in callee for frag in _SEEDY_CALL_FRAGMENTS):
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Call) and call_name(arg) in _WALL_CLOCK_CALLS:
                    yield Finding(
                        code="CDT004",
                        message=(
                            f"wall-clock value fed to `{name}(...)`: seed material must "
                            "be deterministic, not time-derived"
                        ),
                        path=ctx.path,
                        line=arg.lineno,
                        col=arg.col_offset,
                        severity=Severity.ERROR,
                    )
