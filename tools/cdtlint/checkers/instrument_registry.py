"""CDT006: instrument-registry + observability-doc consistency.

The CDT005 knob-registry idiom applied to metrics: every ``cdt_*``
instrument the code can emit must be

1. declared in the canonical instrument registry
   (``comfyui_distributed_tpu/telemetry/instruments.py``) — a factory
   call with a literal metric name anywhere else is a finding (the
   registry is how one test/lint pass can see the whole vocabulary),
   and
2. documented in ``docs/observability.md`` (the operator-facing
   catalogue).

And *vice versa*: every ``cdt_*`` name the doc mentions must be
declared by the registry — a renamed or deleted instrument must not
leave a ghost row operators grep for. ``KNOWN_EXTRA`` lists the few
names declared outside the registry by construction (currently the
metrics-registry-internal overflow counter, whose name is a class
attribute, not a literal).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from ..core import Finding, ProjectContext, Severity
from ..registry import project_checker

INSTRUMENTS_PATH = "comfyui_distributed_tpu/telemetry/instruments.py"
OBSERVABILITY_DOC_PATH = "docs/observability.md"

_METRIC_FACTORIES = {"counter", "gauge", "histogram"}
_DOC_METRIC_RE = re.compile(r"\bcdt_[a-z][a-z0-9_]*\b")

# Metric names emitted by code but declared outside the instrument
# registry by construction. Keep this list SHORT and justified: every
# entry is a name the AST scan cannot see as a registry declaration.
KNOWN_EXTRA = {
    # telemetry/metrics.py MetricsRegistry.OVERFLOW_COUNTER_NAME — the
    # cardinality-cap accounting counter is created by the registry
    # itself (class attribute, not a literal factory arg).
    "cdt_metric_series_overflow_total",
}


def _metric_declarations(ctx) -> Iterator[tuple[str, int]]:
    """(metric name, line) for every literal registry-factory call."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not (
            isinstance(func, ast.Attribute) and func.attr in _METRIC_FACTORIES
        ):
            continue
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        name = node.args[0].value
        if name.startswith("cdt_"):
            yield name, node.lineno


@project_checker(
    "CDT006",
    "instrument-registry",
    "cdt_* metrics must be declared in telemetry/instruments.py and "
    "documented in docs/observability.md (and the doc must not mention "
    "undeclared metrics)",
)
def check_instrument_registry(project: ProjectContext) -> Iterator[Finding]:
    registry_ctx = project.get(INSTRUMENTS_PATH)
    if registry_ctx is None:
        yield Finding(
            code="CDT006",
            message=(
                f"instrument registry {INSTRUMENTS_PATH} is missing from "
                "the scan set"
            ),
            path=INSTRUMENTS_PATH,
            line=1,
            col=0,
            severity=Severity.ERROR,
        )
        return
    declared: dict[str, int] = {}
    for name, lineno in _metric_declarations(registry_ctx):
        declared.setdefault(name, lineno)

    # every OTHER file declaring a literal cdt_* instrument breaks the
    # one-registry idiom (call sites fetch accessors, never name
    # strings inline)
    for ctx in project.files:
        if ctx.path == INSTRUMENTS_PATH:
            continue
        for name, lineno in _metric_declarations(ctx):
            yield Finding(
                code="CDT006",
                message=(
                    f"metric `{name}` is declared outside the instrument "
                    f"registry; move the declaration into "
                    f"{INSTRUMENTS_PATH} and fetch it via an accessor"
                ),
                path=ctx.path,
                line=lineno,
                col=0,
                severity=Severity.ERROR,
            )

    doc_path = os.path.join(project.root, OBSERVABILITY_DOC_PATH)
    if not os.path.exists(doc_path):
        yield Finding(
            code="CDT006",
            message=(
                f"{OBSERVABILITY_DOC_PATH} does not exist; the metric "
                "catalogue must document every declared instrument"
            ),
            path=INSTRUMENTS_PATH,
            line=1,
            col=0,
            severity=Severity.ERROR,
        )
        return
    with open(doc_path, "r", encoding="utf-8") as fh:
        documented = set(_DOC_METRIC_RE.findall(fh.read()))
    # histogram exposition suffixes in doc prose resolve to their base
    # instrument (`cdt_x_seconds_bucket` documents `cdt_x_seconds`)
    for name in list(documented):
        for suffix in ("_bucket", "_sum", "_count"):
            base = name.removesuffix(suffix)
            if base != name and (base in declared or base in KNOWN_EXTRA):
                documented.discard(name)
                documented.add(base)

    for name in sorted(set(declared) - documented):
        yield Finding(
            code="CDT006",
            message=(
                f"metric `{name}` is declared but missing from "
                f"{OBSERVABILITY_DOC_PATH}; add it to the catalogue"
            ),
            path=INSTRUMENTS_PATH,
            line=declared[name],
            col=0,
            severity=Severity.ERROR,
        )
    for name in sorted(documented - set(declared) - KNOWN_EXTRA):
        yield Finding(
            code="CDT006",
            message=(
                f"{OBSERVABILITY_DOC_PATH} documents `{name}` but no such "
                f"instrument is declared in {INSTRUMENTS_PATH}; fix the "
                "doc or restore the declaration"
            ),
            path=INSTRUMENTS_PATH,
            line=1,
            col=0,
            severity=Severity.ERROR,
        )
