"""CDT001: blocking calls lexically inside ``async def`` bodies.

The serving stack is a single asyncio loop per process; one
``time.sleep`` / sync HTTP request / ``threading.Lock.acquire`` in a
coroutine stalls every job, heartbeat, and WebSocket on that loop. The
sanctioned pattern is executor-wrapping (see
``utils/config.config_transaction``: ``await
loop.run_in_executor(None, _txn_lock.acquire)``) — which passes the
callable *uncalled* and therefore does not trip this checker.

Nested synchronous ``def``s inside a coroutine are exempt: they are
routinely handed to ``run_in_executor`` / ``asyncio.to_thread`` and run
off-loop.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (
    FileContext,
    Finding,
    Severity,
    call_name,
    collect_lock_names,
    lock_ref_name,
    walk_scope,
)
from ..registry import checker

# Dotted call names that block the calling thread. Matched against the
# lexically-resolved name, so aliased imports (``from time import
# sleep``) are matched via the bare-name entries too.
BLOCKING_CALLS: dict[str, str] = {
    "time.sleep": "blocks the event loop; use `await asyncio.sleep(...)`",
    "requests.get": "sync HTTP on the event loop; use the shared aiohttp session",
    "requests.post": "sync HTTP on the event loop; use the shared aiohttp session",
    "requests.put": "sync HTTP on the event loop; use the shared aiohttp session",
    "requests.delete": "sync HTTP on the event loop; use the shared aiohttp session",
    "requests.head": "sync HTTP on the event loop; use the shared aiohttp session",
    "requests.request": "sync HTTP on the event loop; use the shared aiohttp session",
    "urllib.request.urlopen": "sync HTTP on the event loop; use the shared aiohttp session",
    "subprocess.run": "blocks until the child exits; use `asyncio.create_subprocess_exec`",
    "subprocess.call": "blocks until the child exits; use `asyncio.create_subprocess_exec`",
    "subprocess.check_call": "blocks until the child exits; use `asyncio.create_subprocess_exec`",
    "subprocess.check_output": "blocks until the child exits; use `asyncio.create_subprocess_exec`",
    "os.system": "blocks until the child exits; use `asyncio.create_subprocess_exec`",
    "os.popen": "blocks until the child exits; use `asyncio.create_subprocess_exec`",
    "socket.create_connection": "sync connect on the event loop; use loop.sock_connect / aiohttp",
    "socket.getaddrinfo": "sync DNS on the event loop; use loop.getaddrinfo",
    "shutil.copyfile": "sync bulk file I/O on the event loop; executor-wrap it",
    "shutil.copytree": "sync bulk file I/O on the event loop; executor-wrap it",
    "shutil.rmtree": "sync bulk file I/O on the event loop; executor-wrap it",
    "open": "sync file I/O on the event loop; move the open/read/write into an "
    "executor-wrapped sync helper",
}

# Path-style bulk I/O method names (receiver type is unresolvable
# statically; these names are only used on pathlib.Path objects in this
# codebase, so a method-name match is a finding).
BLOCKING_METHODS = {"read_text", "write_text", "read_bytes", "write_bytes"}


def _iter_async_defs(tree: ast.Module) -> Iterator[ast.AsyncFunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node


def _from_import_aliases(tree: ast.Module) -> dict[str, str]:
    """``from time import sleep as zzz`` -> {"zzz": "time.sleep"} so
    bare-name calls of blocking functions resolve to their dotted form
    (and ``from asyncio import sleep`` resolves to the *harmless*
    ``asyncio.sleep``, not a false positive)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                aliases[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return aliases


@checker(
    "CDT001",
    "blocking-call-in-async",
    "event-loop-blocking call (sleep / sync HTTP / subprocess / lock acquire) inside `async def`",
)
def check_blocking_async(ctx: FileContext) -> Iterator[Finding]:
    threading_locks, _ = collect_lock_names(ctx.tree)
    aliases = _from_import_aliases(ctx.tree)
    for fn in _iter_async_defs(ctx.tree):
        for node in walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in aliases:
                name = aliases[name]
            if name in BLOCKING_CALLS:
                yield Finding(
                    code="CDT001",
                    message=f"`{name}(...)` in `async def {fn.name}`: {BLOCKING_CALLS[name]}",
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    severity=Severity.ERROR,
                )
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in BLOCKING_METHODS
            ):
                yield Finding(
                    code="CDT001",
                    message=(
                        f"`.{node.func.attr}(...)` in `async def {fn.name}`: sync file "
                        "I/O on the event loop; executor-wrap it"
                    ),
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    severity=Severity.ERROR,
                )
                continue
            # <threading lock>.acquire() called (not merely referenced)
            # on the loop. Passing the bound method to an executor is
            # an Attribute load, not a Call, and stays clean.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
                and lock_ref_name(node.func.value) in threading_locks
            ):
                yield Finding(
                    code="CDT001",
                    message=(
                        f"threading lock `.acquire()` in `async def {fn.name}` blocks the "
                        "event loop; `await loop.run_in_executor(None, lock.acquire)` instead"
                    ),
                    path=ctx.path,
                    line=node.lineno,
                    col=node.col_offset,
                    severity=Severity.ERROR,
                )
