"""Import every checker module so decorators populate the registry."""

from . import blocking_async  # noqa: F401  CDT001
from . import lock_discipline  # noqa: F401  CDT002
from . import tracing_hygiene  # noqa: F401  CDT003
from . import determinism  # noqa: F401  CDT004
from . import registry_consistency  # noqa: F401  CDT005
from . import instrument_registry  # noqa: F401  CDT006
from . import host_sync  # noqa: F401  CDT007
