"""Core datatypes and AST helpers shared by all cdt-lint checkers."""

from __future__ import annotations

import ast
import enum
import re
from dataclasses import dataclass, field
from typing import Iterator, Optional


class Severity(str, enum.Enum):
    ERROR = "error"
    WARNING = "warning"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value


@dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to a source line."""

    code: str  # e.g. "CDT001"
    message: str
    path: str  # repo-root-relative, forward slashes
    line: int  # 1-based
    col: int  # 0-based, matches ast col_offset
    severity: Severity = Severity.ERROR

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.code} [{self.severity}] {self.message}"

    def as_json(self) -> dict:
        return {
            "code": self.code,
            "message": self.message,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": str(self.severity),
        }


# ``# cdt: noqa`` (blanket) or ``# cdt: noqa[CDT001]`` / ``[CDT001,CDT002]``
_NOQA_RE = re.compile(r"#\s*cdt:\s*noqa(?:\[([A-Z0-9,\s]+)\])?", re.IGNORECASE)


def parse_noqa(lines: list[str]) -> dict[int, Optional[frozenset[str]]]:
    """Map 1-based line number -> suppressed codes (None = all codes)."""
    out: dict[int, Optional[frozenset[str]]] = {}
    for i, text in enumerate(lines, start=1):
        if "noqa" not in text:
            continue
        m = _NOQA_RE.search(text)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = frozenset(c.strip().upper() for c in m.group(1).split(",") if c.strip())
    return out


@dataclass
class FileContext:
    """Parsed view of one source file handed to per-file checkers."""

    path: str  # repo-root-relative, forward slashes
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    @classmethod
    def parse(cls, path: str, source: str) -> "FileContext":
        tree = ast.parse(source)
        return cls(path=path, source=source, tree=tree, lines=source.splitlines())

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


@dataclass
class ProjectContext:
    """Whole-scan view handed to project-level checkers (CDT005)."""

    root: str  # absolute repo root
    files: list[FileContext]

    def get(self, path: str) -> Optional[FileContext]:
        for ctx in self.files:
            if ctx.path == path:
                return ctx
        return None


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """Render ``a.b.c`` attribute/name chains; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


THREADING_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
}

ASYNCIO_LOCK_FACTORIES = {
    "asyncio.Lock",
    "asyncio.Condition",
    "asyncio.Semaphore",
    "asyncio.BoundedSemaphore",
}


def collect_lock_names(tree: ast.Module) -> tuple[set[str], set[str]]:
    """Names (bare or ``self.<attr>`` attr names) bound to lock factories.

    Returns ``(threading_locks, asyncio_locks)``. Attribute assignments
    record just the attribute name, so a later ``self._lock`` /
    ``cls._lock`` / ``obj._lock`` use matches by attr. A name bound to
    both kinds anywhere in the file is dropped from both sets rather
    than guessed at.
    """
    threading_locks: set[str] = set()
    asyncio_locks: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        factory = call_name(value)
        if factory in THREADING_LOCK_FACTORIES:
            dest = threading_locks
        elif factory in ASYNCIO_LOCK_FACTORIES:
            dest = asyncio_locks
        else:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for target in targets:
            if isinstance(target, ast.Name):
                dest.add(target.id)
            elif isinstance(target, ast.Attribute):
                dest.add(target.attr)
    ambiguous = threading_locks & asyncio_locks
    return threading_locks - ambiguous, asyncio_locks - ambiguous


def lock_ref_name(node: ast.AST) -> Optional[str]:
    """The comparable name for a lock reference: bare name or final attr."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def walk_scope(node: ast.AST, *, skip_nested_functions: bool = True) -> Iterator[ast.AST]:
    """Walk ``node``'s body without descending into nested function
    scopes (nested defs/lambdas run under their own rules — e.g. they
    may be executor-submitted from an async def)."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if skip_nested_functions and isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(child))


def imported_modules(tree: ast.Module) -> set[str]:
    """Top-level module names imported as themselves (``import random``
    -> {"random"}; ``import numpy as np`` -> {"np"} keyed by alias)."""
    mods: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                mods.add(alias.asname or alias.name.split(".")[0])
    return mods
