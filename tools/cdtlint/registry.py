"""Checker registry: checkers self-register via decorators at import.

Two kinds:

- per-file checkers (``@checker``) get a :class:`FileContext` and yield
  findings about that file in isolation;
- project checkers (``@project_checker``) get the whole
  :class:`ProjectContext` after every file parsed — for cross-file
  invariants like the env-knob registry (CDT005).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Iterable

from .core import FileContext, Finding, ProjectContext

FileCheckFn = Callable[[FileContext], Iterable[Finding]]
ProjectCheckFn = Callable[[ProjectContext], Iterable[Finding]]


@dataclass(frozen=True)
class CheckerInfo:
    code: str
    name: str
    description: str
    fn: Callable
    scope: str  # "file" | "project"


_CHECKERS: dict[str, CheckerInfo] = {}


def _register(code: str, name: str, description: str, fn: Callable, scope: str) -> None:
    if code in _CHECKERS:
        raise ValueError(f"duplicate checker code {code}")
    _CHECKERS[code] = CheckerInfo(code=code, name=name, description=description, fn=fn, scope=scope)


def checker(code: str, name: str, description: str) -> Callable[[FileCheckFn], FileCheckFn]:
    def deco(fn: FileCheckFn) -> FileCheckFn:
        _register(code, name, description, fn, "file")
        return fn

    return deco


def project_checker(code: str, name: str, description: str) -> Callable[[ProjectCheckFn], ProjectCheckFn]:
    def deco(fn: ProjectCheckFn) -> ProjectCheckFn:
        _register(code, name, description, fn, "project")
        return fn

    return deco


def all_checkers() -> dict[str, CheckerInfo]:
    # Import side effect populates the registry exactly once.
    from . import checkers  # noqa: F401

    return dict(sorted(_CHECKERS.items()))
