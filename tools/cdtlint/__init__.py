"""cdt-lint: project-specific static analysis for comfyui-distributed-tpu.

Stdlib-``ast``-based (zero dependencies, mirroring the telemetry
subsystem's ethos) checkers that enforce the concurrency, determinism,
and JAX-tracing invariants the serving stack's correctness rests on:

- CDT001 blocking-call-in-async: no event-loop blocking calls lexically
  inside ``async def`` bodies.
- CDT002 lock-discipline: ``threading.Lock`` never held across an
  ``await``; ``asyncio.Lock`` never touched from sync code.
- CDT003 jax-tracing-hygiene: no host-sync / Python-entropy operations
  reachable inside jit/vmap-traced functions.
- CDT004 determinism: no unsorted set / filesystem iteration or
  wall-clock seed material in the modules backing the bit-identical
  canvas guarantee.
- CDT005 registry-consistency: every ``CDT_*`` env knob read in code is
  declared in the knob registry and documented; ``cdt_*`` metric names
  follow the declared conventions.
- CDT006 instrument-registry: every ``cdt_*`` instrument is declared in
  ``telemetry/instruments.py`` (never inline at a call site) and
  documented in docs/observability.md's catalogue — and the doc
  mentions no undeclared metric.

Suppression: append ``# cdt: noqa[CDT00X]`` (or a bare ``# cdt: noqa``)
to the offending line. Grandfathered findings live in
``tools/cdtlint/baseline.json`` with an inline justification each; the
CI gate fails on any finding that is neither suppressed nor baselined,
and on stale baseline entries (so the baseline can only shrink).

See docs/static-analysis.md for the checker catalogue and policy.
"""

from .core import Finding, Severity, FileContext, ProjectContext  # noqa: F401
from .registry import all_checkers, checker, project_checker  # noqa: F401
from .runner import DEFAULT_SCAN_PATHS, run_lint, LintResult  # noqa: F401
from .baseline import Baseline, fingerprint  # noqa: F401

__all__ = [
    "Finding",
    "Severity",
    "FileContext",
    "ProjectContext",
    "all_checkers",
    "checker",
    "project_checker",
    "run_lint",
    "LintResult",
    "Baseline",
    "fingerprint",
    "DEFAULT_SCAN_PATHS",
]
